"""Data-parallel training of an MLP with JaxTrainer (the SURVEY §7.2
minimum end-to-end slice): 2 workers, synthetic data, checkpoint+report.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/train_mnist_mlp.py
"""
import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, ScalingConfig


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    ctx = train.get_context()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(128)(x))
            return nn.Dense(10)(x)

    model = MLP()
    rng = np.random.default_rng(ctx.world_rank)
    x = rng.normal(size=(512, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(512,))

    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 784)))
    tx = optax.adam(config["lr"])
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    for epoch in range(config["epochs"]):
        params, opt_state, loss = step(params, opt_state, x, y)
        train.report({"epoch": epoch, "loss": float(loss)})


if __name__ == "__main__":
    ray_tpu.init(num_cpus=4, num_tpus=0)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"lr": 1e-3, "epochs": 5},
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=1),
    )
    result = trainer.fit()
    print("final:", result.metrics)
    ray_tpu.shutdown()
