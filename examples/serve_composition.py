"""Serve: HTTP ingress + model composition + dynamic batching.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/serve_composition.py
"""
import json
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_cpus=0.2)
class Scorer:
    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def __call__(self, texts):
        return [len(t) % 10 for t in texts]


@serve.deployment
class Router:
    def __init__(self, scorer):
        self.scorer = scorer

    async def __call__(self, request):
        text = request.json()["text"]
        score = await self.scorer.remote(text)
        return {"text": text, "score": score}


if __name__ == "__main__":
    ray_tpu.init(num_cpus=4, num_tpus=0)
    serve.run(Router.bind(Scorer.bind()), name="scoring",
              route_prefix="/score", http_port=18925)
    req = urllib.request.Request(
        "http://127.0.0.1:18925/score",
        data=json.dumps({"text": "hello ray_tpu"}).encode(),
        method="POST")
    with urllib.request.urlopen(req, timeout=60) as resp:
        print("response:", json.loads(resp.read()))
    serve.shutdown()
    ray_tpu.shutdown()
