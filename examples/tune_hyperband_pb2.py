"""Tune: synchronous HyperBand with checkpointable trainables, then
PB2's GP-bandit population training, with CSV/JSON logger callbacks.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/tune_hyperband_pb2.py
"""
import os
import tempfile

import ray_tpu
from ray_tpu import tune
from ray_tpu.train.config import RunConfig


class Quadratic(tune.Trainable):
    """Score climbs toward 10 at a rate set by lr; best lr = 0.5."""

    def setup(self, config):
        self.lr = config["lr"]
        self.val = 0.0

    def step(self):
        self.val += (1.0 - abs(self.lr - 0.5)) * (10 - self.val) * 0.1
        return {"score": self.val}

    def save_checkpoint(self, path):
        with open(os.path.join(path, "v"), "w") as f:
            f.write(str(self.val))

    def load_checkpoint(self, path):
        with open(os.path.join(path, "v")) as f:
            self.val = float(f.read())


if __name__ == "__main__":
    ray_tpu.init(num_cpus=4, num_tpus=0)
    storage = tempfile.mkdtemp()

    # Synchronous HyperBand: brackets pause at rung milestones, keep
    # the top 1/eta, resume survivors.
    grid = tune.Tuner(
        Quadratic,
        param_space={"lr": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=9,
            scheduler=tune.HyperBandScheduler(max_t=9,
                                              reduction_factor=3)),
        run_config=RunConfig(name="hb", storage_path=storage,
                             callbacks=[tune.CSVLoggerCallback(),
                                        tune.JsonLoggerCallback()]),
    ).fit()
    best = grid.get_best_result()
    print("HyperBand best:", round(best.metrics["score"], 3))

    # PB2: exploit + GP-bandit hyperparameter selection.
    grid = tune.Tuner(
        Quadratic,
        param_space={"lr": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=4,
            scheduler=tune.PB2(hyperparam_bounds={"lr": (0.0, 1.0)},
                               perturbation_interval=3, seed=0)),
        run_config=RunConfig(name="pb2", storage_path=storage,
                             stop={"training_iteration": 15}),
    ).fit()
    print("PB2 best:", round(grid.get_best_result().metrics["score"], 3))
    ray_tpu.shutdown()
