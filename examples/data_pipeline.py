"""Data: distributed ETL -> shuffle -> batched iteration into JAX.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/data_pipeline.py
"""
import ray_tpu
from ray_tpu import data as rd

if __name__ == "__main__":
    ray_tpu.init(num_cpus=4, num_tpus=0)
    ds = (rd.range(10_000, parallelism=8)
          .map_batches(lambda b: {"item": b["item"],
                                  "sq": b["item"] ** 2})
          .filter(lambda r: r["item"] % 3 == 0)
          .random_shuffle(seed=0))
    print("rows:", ds.count())
    print("mean of squares:", ds.mean(on="sq"))
    for i, batch in enumerate(ds.iter_batches(batch_size=512,
                                              batch_format="jax")):
        if i == 0:
            print("first batch:", {k: v.shape for k, v in batch.items()})
    ray_tpu.shutdown()
