"""Offline RL: collect behavior data, estimate a policy off-policy,
then behavior-clone from the dataset.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/rllib_offline_bc.py
"""
import tempfile

import jax

import ray_tpu
from ray_tpu import rllib as rl
from ray_tpu.rllib.env import Space
from ray_tpu.rllib.rl_module import RLModuleSpec

if __name__ == "__main__":
    ray_tpu.init(num_cpus=2, num_tpus=0)
    data_dir = tempfile.mkdtemp() + "/episodes"

    # 1. Collect episodes from a (here: untrained) behavior policy.
    spec = RLModuleSpec(Space.box((4,)), Space.discrete(2))
    params = spec.build().init_params(jax.random.PRNGKey(0))
    with rl.JsonWriter(data_dir) as writer:
        episodes = rl.collect_episodes(
            "CartPole-v1", spec, params,
            num_episodes=20, num_envs=4, seed=0, writer=writer)
    print(f"collected {len(episodes)} episodes -> {data_dir}")

    # 2. Off-policy estimate of the SAME policy: v_gain ~= 1.
    est = rl.WeightedImportanceSampling(spec, params, gamma=0.99)
    print("WIS estimate:", est.estimate(episodes))

    # 3. Behavior-clone the dataset policy.
    bc = (rl.BCConfig()
          .offline_data(input_=data_dir)
          .training(lr=1e-3, train_batch_size=128)
          .build())
    for i in range(20):
        result = bc.step()
    print(f"BC loss after {result['training_iteration']} iters:",
          round(result["bc_loss"], 4))
    ray_tpu.shutdown()
