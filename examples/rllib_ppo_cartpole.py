"""RLlib: PPO on the built-in vectorized CartPole.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/rllib_ppo_cartpole.py
"""
import ray_tpu
from ray_tpu import rllib as rl

if __name__ == "__main__":
    ray_tpu.init(num_cpus=4, num_tpus=0)
    algo = (rl.PPOConfig()
            .environment("CartPole-v1", num_envs_per_env_runner=8)
            .env_runners(num_env_runners=2, rollout_fragment_length=64,
                         num_cpus_per_env_runner=0.5)
            .training(lr=1e-3)
            .debugging(seed=0)
            .build())
    for i in range(5):
        result = algo.step()
        print(f"iter {i}: return={result.get('episode_return_mean')}")
    algo.cleanup()
    ray_tpu.shutdown()
