"""Experimental substrate: pre-allocated shared-memory channels and the
compiled-DAG execution path built on them (reference:
python/ray/experimental/channel.py, python/ray/dag/compiled_dag_node.py).
"""

from ray_tpu.experimental.channel import (  # noqa: F401
    ChannelClosed,
    ShmChannel,
)

__all__ = ["ShmChannel", "ChannelClosed"]
