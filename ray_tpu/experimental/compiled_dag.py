"""Compiled DAG execution: pinned actor loops over shm channels.

Reference: python/ray/dag/compiled_dag_node.py:19-46 (``dag.
experimental_compile()`` — allocate channels once, pin an execution
loop on every participating actor, and drive repeated executions with
zero per-call task overhead) and python/ray/experimental/channel.py:49
(the channel substrate, here ``ShmChannel``).

Topology: the driver creates one SPSC channel per producer→consumer
edge (fan-out = one channel per consumer), then starts a
``__rtpu_channel_loop__`` actor task on every participating actor —
that task attaches the actor's channels and loops: read args → run
method → write result, until its input channels close. ``execute()``
then costs two channel hops per actor in the chain instead of two RPC
round-trips, which is the compiled path's whole value: p50 latency
drops by an order of magnitude (see scripts/microbenchmark.py
``compiled_dag_roundtrip``).

Scope: actor-method nodes only (a plain task has no pinned process to
loop on — the reference has the same constraint); one positional
InputNode; every channel endpoint must live on the same host (channels
are posix shm; the reference's cross-host channels ride NCCL — ours
would ride ICI collectives inside jit, which is the in-graph pipeline
in parallel/pipeline.py, not this substrate).
"""

from __future__ import annotations

import itertools
import logging
import os
import pickle
import socket
import time
from typing import Any, Dict, List, Optional

from ray_tpu.experimental.channel import ChannelClosed, ShmChannel

logger = logging.getLogger(__name__)

_dag_counter = itertools.count()


class _NodeError:
    """Sentinel carrying an exception raised by a node's method through
    the channels (reference: compiled_dag_node.py wraps per-execution
    errors and keeps the DAG alive). Downstream loops forward it
    without invoking their method; ``execute()`` re-raises it."""

    __slots__ = ("exc", "method")

    def __init__(self, exc: BaseException, method: str):
        self.exc = exc
        self.method = method


def _local_hosts() -> tuple:
    """(addresses that resolve to this machine, confident) — shm channel
    scope. ``confident`` is False when the NIC address couldn't be
    determined (no default route): a non-loopback advertised address
    then CAN'T be disproven local, so the caller must not reject on it
    (the attach timeout stays the backstop)."""
    hosts = {"127.0.0.1", "localhost", "0.0.0.0", "::1", ""}
    confident = False
    try:
        name = socket.gethostname()
        hosts.add(name)
        hosts.update(info[4][0]
                     for info in socket.getaddrinfo(name, None))
    except OSError:
        pass
    # The outward-facing interface IP — /etc/hosts often maps the
    # hostname to 127.0.1.1 only, while node agents advertise the NIC
    # address (same trick as train/worker_group.py node_ip()).
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            hosts.add(s.getsockname()[0])
            confident = True
        finally:
            s.close()
    except OSError:
        pass
    return hosts, confident


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


def run_channel_loop(instance, config_blob: bytes) -> dict:
    """Body of the ``__rtpu_channel_loop__`` actor task (executed on
    the actor's execution thread, with ``self`` = the actor instance).
    Returns loop statistics when the upstream closes."""
    config = pickle.loads(config_blob)
    in_chans: Dict[str, ShmChannel] = {}
    out_chans: Dict[str, ShmChannel] = {}
    for node in config["nodes"]:
        for kind, ref in list(node["args"]) + list(
                node["kwargs"].values()):
            if kind == "chan" and ref not in in_chans:
                in_chans[ref] = ShmChannel.attach(ref)
        for name in node["outputs"]:
            if name not in out_chans:
                out_chans[name] = ShmChannel.attach(name)
    iterations = 0
    debug = os.environ.get("RAY_TPU_CDAG_DEBUG")
    waits: list = []
    procs: list = []
    try:
        while True:
            # One DAG tick: every node bound to this actor, topo order.
            t0 = time.perf_counter() if debug else 0.0
            for node in config["nodes"]:

                def resolve(enc):
                    kind, ref = enc
                    return in_chans[ref].read() if kind == "chan" else ref

                args = [resolve(a) for a in node["args"]]
                kwargs = {k: resolve(v)
                          for k, v in node["kwargs"].items()}
                t1 = time.perf_counter() if debug else 0.0
                # An upstream error flows through untouched; otherwise a
                # method exception becomes a _NodeError written to the
                # outputs so execute() re-raises it while the loop (and
                # the DAG) stays alive for the next tick.
                value = next(
                    (v for v in itertools.chain(args, kwargs.values())
                     if isinstance(v, _NodeError)), None)
                if value is None:
                    try:
                        method = getattr(instance, node["method"])
                        value = method(*args, **kwargs)
                    except Exception as exc:  # noqa: BLE001
                        try:
                            pickle.dumps(exc)
                        except Exception:
                            exc = RuntimeError(
                                f"{type(exc).__name__}: {exc}")
                        value = _NodeError(exc, node["method"])
                for name in node["outputs"]:
                    out_chans[name].write(value)
            if debug:
                waits.append(t1 - t0)
                procs.append(time.perf_counter() - t1)
            iterations += 1
    except ChannelClosed:
        pass
    finally:
        if debug and waits:
            import statistics as _st
            import sys as _sys

            print(f"[cdag-loop] iters={iterations} "
                  f"wait p50={_st.median(waits)*1e6:.0f}us "
                  f"proc p50={_st.median(procs)*1e6:.0f}us",
                  file=_sys.stderr, flush=True)
        for ch in out_chans.values():
            ch.close()
        for ch in list(in_chans.values()) + list(out_chans.values()):
            ch.destroy()
    return {"iterations": iterations}


# ---------------------------------------------------------------------------
# driver side
# ---------------------------------------------------------------------------


class CompiledDag:
    """Driver handle for a compiled DAG (reference:
    compiled_dag_node.py's CompiledDAG). Create via
    ``dag_node.experimental_compile()``."""

    def __init__(self, root, buffer_size_bytes: int = 1 << 20,
                 max_inflight: int = 8):
        from ray_tpu.dag import ClassMethodNode, InputNode

        self._torn_down = False
        dag_id = f"{os.getpid()}_{next(_dag_counter)}"
        order = root.topo_order()
        self._root = root
        methods = [n for n in order if isinstance(n, ClassMethodNode)]
        inputs = [n for n in order if isinstance(n, InputNode)]
        if not methods:
            raise ValueError(
                "experimental_compile() needs at least one actor-method "
                "node (plain tasks have no pinned process to loop on)")
        for n in order:
            if not isinstance(n, (ClassMethodNode, InputNode)):
                raise ValueError(
                    f"compiled DAGs support actor-method and input "
                    f"nodes only, got {n!r}")
        if len(inputs) > 1:
            raise ValueError("compiled DAGs take a single InputNode")
        # Fail cross-host placement here, with a real error — otherwise
        # the remote loop's ShmChannel.attach times out 30s in and
        # execute() just hangs (advisor r4). Runs before any shm
        # segment is allocated so a raise leaks nothing.
        self._validate_same_host(
            {n.actor_handle._actor_id: n.actor_handle for n in methods}
            .values())

        # consumer edges: node -> list of channel names it reads, in arg
        # order; producer -> channels it writes.
        self._input_channels: List[ShmChannel] = []
        chan_defs: List[str] = []
        node_outputs: Dict[int, List[str]] = {}
        node_args: Dict[int, list] = {}
        ctr = itertools.count()

        def new_chan(tag: str) -> str:
            return f"rtpu_cdag_{dag_id}_{next(ctr)}_{tag[:8]}"

        node_kwargs: Dict[int, dict] = {}

        def encode(arg):
            if isinstance(arg, InputNode):
                name = new_chan("in")
                chan_defs.append(name)
                self._input_channel_names = getattr(
                    self, "_input_channel_names", [])
                self._input_channel_names.append(name)
                return ("chan", name)
            if isinstance(arg, ClassMethodNode):
                name = new_chan("mid")
                chan_defs.append(name)
                node_outputs.setdefault(id(arg), []).append(name)
                return ("chan", name)
            return ("const", arg)

        for node in methods:
            # kwargs carry DAG nodes too — they must be wired, not
            # pickled as constants (a raw node object reaching the
            # method would be silent garbage).
            node_args[id(node)] = [encode(a) for a in node.args]
            node_kwargs[id(node)] = {k: encode(v)
                                     for k, v in node.kwargs.items()}
        # Root output -> driver.
        out_name = new_chan("out")
        chan_defs.append(out_name)
        node_outputs.setdefault(id(root), []).append(out_name)

        # Driver owns every segment (single point of cleanup).
        self._channels = {
            name: ShmChannel.create(name, nslots=max_inflight,
                                    slot_bytes=buffer_size_bytes)
            for name in chan_defs
        }
        self._input_channels = [
            self._channels[n]
            for n in getattr(self, "_input_channel_names", [])]
        self._output_channel = self._channels[out_name]
        if not self._input_channels:
            # Without a driver-fed edge the loops would free-run the
            # methods on compile and teardown could never signal EOS.
            for ch in self._channels.values():
                ch.destroy()
            raise ValueError(
                "compiled DAGs need an InputNode edge driving them "
                "(use node.execute() for constant-only graphs)")

        # Group nodes per actor (by id — two handles to one actor must
        # share ONE loop, a second would queue behind it forever),
        # preserving topo order, and start the loops.
        per_actor: Dict[Any, tuple] = {}
        for node in methods:
            cfg = {
                "method": node.method_name,
                "args": node_args[id(node)],
                "kwargs": node_kwargs[id(node)],
                "outputs": node_outputs.get(id(node), []),
            }
            key = node.actor_handle._actor_id
            per_actor.setdefault(key, (node.actor_handle, []))[1].append(
                cfg)
        from ray_tpu.api import ActorMethod

        self.loop_errors: List[BaseException] = []
        self._loop_refs = []
        for handle, nodes in per_actor.values():
            blob = pickle.dumps({"nodes": nodes})
            # Direct ActorMethod: handle.__getattr__ blocks underscore
            # names by design.
            ref = ActorMethod(handle, "__rtpu_channel_loop__").remote(blob)
            self._loop_refs.append(ref)

    def _validate_same_host(self, handles, timeout: float = 2.0):
        """Every channel endpoint must share the driver's physical host
        (posix shm). Resolve each actor's placement via the actor table
        and raise a clear error for cross-host edges; the TPU-native
        cross-host substrate is the in-graph ICI pipeline
        (parallel/pipeline.py), not shm channels.

        Best-effort with a small budget: an actor still PENDING past it
        is skipped (the attach timeout remains the backstop) — compile
        must not block 30s on the common no-warmup
        ``A.remote(); compile()`` pattern."""
        from ray_tpu import api as _api

        cw = _api._require_worker()
        local, confident = _local_hosts()
        deadline = time.monotonic() + timeout
        for handle in handles:
            aid = handle._actor_id.hex()
            delay = 0.02
            while True:
                reply = cw.loop_thread.run(cw.head.call(
                    "get_actor_info", {"actor_id": aid}))
                if reply.get("found"):
                    if reply.get("state") == "DEAD":
                        raise ValueError(
                            f"cannot compile DAG: actor {aid} is dead")
                    addr = reply.get("address")
                    if addr:
                        if addr[0] not in local and confident:
                            raise ValueError(
                                f"compiled DAGs require every actor on "
                                f"the driver's host (channels are posix "
                                f"shm); actor {aid} lives on {addr[0]}. "
                                f"Use the in-graph ICI pipeline "
                                f"(parallel/pipeline.py) for cross-host "
                                f"stages.")
                        break
                if time.monotonic() > deadline:
                    # Placement unresolved (actor still pending past the
                    # budget) — let attach enforce the invariant.
                    break
                time.sleep(delay)
                delay = min(delay * 2, 0.2)

    def execute(self, *args, timeout: Optional[float] = 60.0) -> Any:
        """One synchronous DAG tick: feed the input, return the root
        node's result. Back-to-back executions pipeline naturally (the
        rings buffer ``max_inflight`` ticks)."""
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._input_channels and not args:
            raise ValueError("DAG has an InputNode; execute(value)")
        for ch in self._input_channels:
            ch.write(args[0] if args else None, timeout=timeout)
        result = self._output_channel.read(timeout=timeout)
        if isinstance(result, _NodeError):
            raise result.exc
        return result

    def teardown(self, timeout: float = 30.0):
        """Close the input edges; loops drain, cascade-close, and their
        actor tasks return. Channel segments are unlinked here."""
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._input_channels:
            ch.close()
        import ray_tpu

        try:
            ray_tpu.get(self._loop_refs, timeout=timeout)
        except Exception as exc:  # noqa: BLE001
            # Teardown still proceeds (actors may legitimately be dead
            # already), but the failure is recorded and logged — a
            # swallowed loop error here is how a broken DAG used to
            # masquerade as a channel timeout (advisor r4).
            self.loop_errors.append(exc)
            logger.warning(
                "compiled DAG loop task failed during teardown: %r", exc)
        for ch in self._channels.values():
            ch.destroy()

    def __del__(self):
        try:
            self.teardown(timeout=5)
        except Exception:
            pass
