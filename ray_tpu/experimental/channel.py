"""Pre-allocated shared-memory ring-buffer channels.

Reference: python/ray/experimental/channel.py:49 (``Channel`` — a
buffer allocated once in the object store that accelerated-DAG actors
write/read without per-message RPCs or allocations). Here the channel
is a fixed ring of slots in ONE posix shm segment, single writer /
single reader (SPSC): the compiled-DAG layer gives every producer →
consumer edge its own channel, which is how MPMC patterns are built
(reference does the same: one channel per reader).

Synchronization is two monotonically-increasing u64 sequence cursors
(write_seq, read_seq) in the segment header. Aligned 8-byte loads and
stores are atomic on every platform CPython runs on, and the payload
is written strictly before the cursor publish (x86 TSO / ARM release
semantics via the interpreter's own barriers), so a reader that
observes ``write_seq > read_seq`` also observes the slot contents.
Waiting is adaptive: a short spin (latency path — the whole point of
channels is the microsecond hop) then escalating sleeps (cpu path).

Values larger than a slot overflow to the object store: the slot then
carries a pickled ObjectRef and the reader dereferences it — the same
escape hatch the reference uses for dynamically-sized returns.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional


class _Sems:
    """Named POSIX semaphores (sem_open/post/timedwait via ctypes) —
    real blocking wakeups between unrelated processes. A spin-sleep
    ladder burns half a scheduler quantum per hop on a busy host;
    sem_post hands the CPU straight to the waiter, which is where the
    channel's microsecond latency comes from."""

    _lib = None

    @classmethod
    def lib(cls):
        if cls._lib is None:
            path = ctypes.util.find_library("pthread") or \
                ctypes.util.find_library("rt")
            lib = ctypes.CDLL(path, use_errno=True) if path \
                else ctypes.CDLL(None, use_errno=True)
            lib.sem_open.restype = ctypes.c_void_p
            lib.sem_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_uint, ctypes.c_uint]
            for fn in ("sem_post", "sem_trywait", "sem_close"):
                getattr(lib, fn).restype = ctypes.c_int
                getattr(lib, fn).argtypes = [ctypes.c_void_p]
            lib.sem_timedwait.restype = ctypes.c_int
            lib.sem_timedwait.argtypes = [ctypes.c_void_p,
                                          ctypes.c_void_p]
            lib.sem_unlink.restype = ctypes.c_int
            lib.sem_unlink.argtypes = [ctypes.c_char_p]
            cls._lib = lib
        return cls._lib


class _Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


_O_CREAT = getattr(os, "O_CREAT", 64)
_SEM_FAILED = ctypes.c_void_p(0).value


class _NamedSem:
    def __init__(self, name: str, create: bool, value: int = 0):
        lib = _Sems.lib()
        self.name = name.encode()
        self._lib = lib
        if create:
            lib.sem_unlink(self.name)  # stale from a crashed run
            handle = lib.sem_open(self.name, _O_CREAT, 0o600, value)
        else:
            # sem_open is variadic; mode/value are ignored without
            # O_CREAT but ctypes' argtypes demand them.
            handle = lib.sem_open(self.name, 0, 0, 0)
        if not handle:
            raise OSError(ctypes.get_errno(),
                          f"sem_open({name}) failed")
        self._h = ctypes.c_void_p(handle)
        self._owner = create

    def post(self):
        self._lib.sem_post(self._h)

    def try_acquire(self) -> bool:
        if self._lib.sem_trywait(self._h) == 0:
            return True
        return False

    def acquire(self, timeout: Optional[float]) -> bool:
        """Blocking (GIL released inside ctypes). False on timeout."""
        if self.try_acquire():
            return True
        if os.environ.get("RAY_TPU_CHANNEL_POLL"):
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            delay = 10e-6
            while True:
                if self.try_acquire():
                    return True
                if (deadline is not None
                        and time.monotonic() > deadline):
                    return False
                time.sleep(delay)
                delay = min(delay * 2, 1e-3)
        deadline = None if timeout is None else time.time() + timeout
        while True:
            abs_t = time.time() + 3600 if deadline is None else deadline
            ts = _Timespec(int(abs_t), int((abs_t % 1) * 1e9))
            rc = self._lib.sem_timedwait(self._h, ctypes.byref(ts))
            if rc == 0:
                return True
            err = ctypes.get_errno()
            if err == errno.EINTR:
                continue
            if err == errno.ETIMEDOUT:
                if deadline is None:
                    continue  # periodic re-arm for infinite waits
                return False
            raise OSError(err, "sem_timedwait failed")

    def close(self):
        try:
            self._lib.sem_close(self._h)
        except Exception:
            pass
        if self._owner:
            try:
                self._lib.sem_unlink(self.name)
            except Exception:
                pass

_MAGIC = 0x52435448  # "RCTH"
_HDR = 64
# header offsets
_OFF_MAGIC = 0
_OFF_NSLOTS = 4
_OFF_SLOT_BYTES = 8
_OFF_WRITE_SEQ = 16
_OFF_READ_SEQ = 24
_OFF_CLOSED = 32

_KIND_INLINE = 0
_KIND_REF = 1
_SLOT_HDR = 8  # u32 len | u8 kind | pad


class ChannelClosed(Exception):
    """The writer closed the channel; no further values will arrive."""


class ShmChannel:
    """SPSC shared-memory ring channel.

    One process calls :meth:`create`, every peer calls :meth:`attach`
    with the returned name. Exactly one process may write; exactly one
    may read (the compiled-DAG layer enforces this by construction).
    """

    def __init__(self, seg: shared_memory.SharedMemory, owner: bool):
        self._seg = seg
        self._owner = owner
        self._buf = seg.buf
        self.nslots = struct.unpack_from("<I", self._buf, _OFF_NSLOTS)[0]
        self.slot_bytes = struct.unpack_from(
            "<Q", self._buf, _OFF_SLOT_BYTES)[0]
        # Blocking wakeups: `items` counts readable slots, `spaces` free
        # ones. Created with the segment; peers attach by name.
        self._items = _NamedSem(f"/{seg.name}.i", create=owner, value=0)
        self._spaces = _NamedSem(f"/{seg.name}.s", create=owner,
                                 value=self.nslots if owner else 0)

    # -- lifecycle -----------------------------------------------------

    @classmethod
    def create(cls, name: str, nslots: int = 8,
               slot_bytes: int = 1 << 20) -> "ShmChannel":
        size = _HDR + nslots * (slot_bytes + _SLOT_HDR)
        seg = shared_memory.SharedMemory(name=name, create=True, size=size)
        struct.pack_into("<I", seg.buf, _OFF_NSLOTS, nslots)
        struct.pack_into("<Q", seg.buf, _OFF_SLOT_BYTES, slot_bytes)
        struct.pack_into("<Q", seg.buf, _OFF_WRITE_SEQ, 0)
        struct.pack_into("<Q", seg.buf, _OFF_READ_SEQ, 0)
        seg.buf[_OFF_CLOSED] = 0
        inst = cls(seg, owner=True)  # creates the semaphores
        # Magic LAST — after header AND semaphores exist: attach() spins
        # on it, so a partially-initialized channel is never observed.
        struct.pack_into("<I", seg.buf, _OFF_MAGIC, _MAGIC)
        return inst

    @classmethod
    def attach(cls, name: str, timeout: float = 30.0) -> "ShmChannel":
        deadline = time.monotonic() + timeout
        while True:
            try:
                seg = shared_memory.SharedMemory(name=name)
            except FileNotFoundError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.005)
                continue
            if struct.unpack_from("<I", seg.buf, _OFF_MAGIC)[0] == _MAGIC:
                return cls(seg, owner=False)
            seg.close()
            if time.monotonic() > deadline:
                raise TimeoutError(f"channel {name} never initialized")
            time.sleep(0.005)

    @property
    def name(self) -> str:
        return self._seg.name

    def close(self):
        """Writer-side: signal end-of-stream to the reader."""
        try:
            self._buf[_OFF_CLOSED] = 1
        except (TypeError, ValueError):
            pass  # segment already destroyed
        # Phantom post: wake a blocked reader so it can observe EOS
        # (it re-checks the cursors and raises ChannelClosed).
        try:
            self._items.post()
        except Exception:
            pass

    def destroy(self):
        self._buf = None
        for sem in (self._items, self._spaces):
            try:
                sem.close()
            except Exception:
                pass
        try:
            self._seg.close()
        except Exception:
            pass
        if self._owner:
            try:
                self._seg.unlink()
            except Exception:
                pass

    # -- cursors -------------------------------------------------------

    def _load(self, off: int) -> int:
        return struct.unpack_from("<Q", self._buf, off)[0]

    def _store(self, off: int, value: int):
        struct.pack_into("<Q", self._buf, off, value)

    def _slot_off(self, seq: int) -> int:
        return _HDR + (seq % self.nslots) * (self.slot_bytes + _SLOT_HDR)

    # -- data path -----------------------------------------------------

    def write_bytes(self, payload: bytes, kind: int = _KIND_INLINE,
                    timeout: Optional[float] = None):
        if len(payload) > self.slot_bytes:
            raise ValueError(
                f"payload {len(payload)}B exceeds slot {self.slot_bytes}B")
        if not self._spaces.acquire(timeout):
            raise TimeoutError("channel write timed out (ring full)")
        wseq = self._load(_OFF_WRITE_SEQ)
        off = self._slot_off(wseq)
        struct.pack_into("<IB", self._buf, off, len(payload), kind)
        self._buf[off + _SLOT_HDR:off + _SLOT_HDR + len(payload)] = payload
        # Publish AFTER the payload is in place, THEN wake the reader.
        self._store(_OFF_WRITE_SEQ, wseq + 1)
        self._items.post()

    def read_bytes(self, timeout: Optional[float] = None):
        while True:
            if not self._items.acquire(timeout):
                raise TimeoutError("channel read timed out")
            rseq = self._load(_OFF_READ_SEQ)
            if self._load(_OFF_WRITE_SEQ) > rseq:
                break
            # Phantom wakeup from close(): drained + closed ⇒ EOS.
            if self._buf[_OFF_CLOSED] == 1:
                self._items.post()  # keep EOS observable for re-reads
                raise ChannelClosed()
        off = self._slot_off(rseq)
        length, kind = struct.unpack_from("<IB", self._buf, off)
        payload = bytes(
            self._buf[off + _SLOT_HDR:off + _SLOT_HDR + length])
        self._store(_OFF_READ_SEQ, rseq + 1)
        self._spaces.post()
        return payload, kind

    def write(self, value: Any, timeout: Optional[float] = None):
        """Serialize and write one value; values that don't fit a slot
        overflow to the object store and ship as a ref."""
        payload = pickle.dumps(value, protocol=5)
        if len(payload) <= self.slot_bytes:
            self.write_bytes(payload, _KIND_INLINE, timeout)
            return
        import ray_tpu

        ref = ray_tpu.put(value)
        self.write_bytes(pickle.dumps(ref, protocol=5), _KIND_REF, timeout)

    def read(self, timeout: Optional[float] = None) -> Any:
        payload, kind = self.read_bytes(timeout)
        value = pickle.loads(payload)
        if kind == _KIND_REF:
            import ray_tpu

            value = ray_tpu.get(value, timeout=timeout)
        return value
