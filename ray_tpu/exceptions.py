"""Exception hierarchy (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Re-raised at ``get`` on the caller with the remote traceback attached
    (reference: RayTaskError in python/ray/exceptions.py).
    """

    def __init__(self, cause_cls_name: str, cause_repr: str, traceback_str: str,
                 task_name: str = ""):
        self.cause_cls_name = cause_cls_name
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.task_name = task_name
        super().__init__(
            f"Task '{task_name}' failed with {cause_cls_name}: {cause_repr}\n"
            f"{traceback_str}"
        )

    def __reduce__(self):
        return (TaskError, (self.cause_cls_name, self.cause_repr,
                            self.traceback_str, self.task_name))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class OutOfMemoryError(WorkerCrashedError):
    """The worker was killed by the node memory monitor (reference:
    ray.exceptions.OutOfMemoryError surfaced by the raylet's
    memory_monitor.h watchdog)."""


class ActorDiedError(RayTpuError):
    """The actor owning the called method is dead."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"Actor {actor_id_hex} is dead: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting or network issue)."""


class ObjectLostError(RayTpuError):
    """An object is no longer reachable and could not be reconstructed."""

    def __init__(self, object_id_hex: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(f"Object {object_id_hex} was lost and could not be "
                         "reconstructed from lineage")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id_hex,))


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store is out of memory (after spilling)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` exceeded its timeout."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class NodeDiedError(RayTpuError):
    """A node (scheduler daemon) died."""


class PendingCallsLimitExceeded(RayTpuError):
    """Back-pressure limit on an actor's pending calls was exceeded."""


class ActorExitSignal(BaseException):
    """Raised by user code (via api.actor_exit) for graceful actor exit.

    BaseException (not RayTpuError) so ordinary `except Exception` blocks in
    user code don't swallow it. Defined here — not in worker_main — because
    the worker runs as __main__ and would otherwise see two distinct classes.
    """


#: Failures that mean the serving PROCESS died or became unreachable —
#: as opposed to the application code raising. Consumers (serve proxy
#: retry-before-first-chunk, router stream-abort attribution) use this
#: to separate "safe to retry / count as replica_death" from user
#: errors that must never be re-executed.
ACTOR_SYSTEM_FAILURES = (ActorDiedError, WorkerCrashedError,
                         ActorUnavailableError, NodeDiedError)
