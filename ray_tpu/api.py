"""Public API.

Reference surface: python/ray/_private/worker.py (init:1229, get:2557,
put/wait/kill/cancel), python/ray/remote_function.py:262 (RemoteFunction),
python/ray/actor.py:830 (ActorClass._remote), actor.py:1193 (ActorHandle).

``init()`` starts the head services in-process (single "head node" with
auto-detected CPU/TPU/memory resources, or a fake multi-node cluster for
tests) and creates the driver's CoreWorker. ``remote`` wraps functions into
``RemoteFunction`` and classes into ``ActorClass``.
"""

from __future__ import annotations

import functools
import inspect
import logging
import os
import tempfile
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions as exc
from ray_tpu.core import object_ref as object_ref_mod
from ray_tpu.core import rpc
from ray_tpu.core.config import Config, get_config, reset_config
from ray_tpu.core.core_worker import CoreWorker, HeadClient
from ray_tpu.core.gcs import LocalPeer
from ray_tpu.core.ids import ActorID, JobID, WorkerID
from ray_tpu.core.node import HeadNode, detect_node_resources
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import (
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

logger = logging.getLogger(__name__)

_init_lock = threading.Lock()
_global_node: Optional[HeadNode] = None
_global_worker: Optional[CoreWorker] = None


def is_initialized() -> bool:
    return object_ref_mod.get_core_worker() is not None


def _require_worker() -> CoreWorker:
    cw = object_ref_mod.get_core_worker()
    if cw is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first"
        )
    return cw


ADDRESS_FILE = os.path.join(tempfile.gettempdir(), "ray_tpu",
                            "ray_current_cluster")


def init(address: Optional[str] = None,
         num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         system_config: Optional[dict] = None,
         namespace: str = "",
         logging_level: int = logging.INFO,
         ignore_reinit_error: bool = False) -> "RuntimeContext":
    """Start the runtime (head node + driver core worker), or attach to
    a running cluster with ``address="host:port"`` / ``address="auto"``
    (reference: ray.init address semantics; discovery through the
    current-cluster file like /tmp/ray/ray_current_cluster)."""
    global _global_node, _global_worker
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return get_runtime_context()
            raise RuntimeError("ray_tpu.init() called twice")
        reset_config()
        config = get_config()
        config.apply_system_config(system_config)
        if object_store_memory:
            config.object_store_memory = object_store_memory

        if address is not None and address.startswith("rtpu://"):
            # Thin-client mode (reference: ray:// Ray Client): ONE
            # outbound connection to a cluster-side client server; the
            # cluster never dials back (NAT'd clients work).
            from ray_tpu import client as _client

            _global_worker = _client.connect(address[len("rtpu://"):],
                                             namespace=namespace)
            return get_runtime_context()
        if address is not None:
            if address == "auto":
                address = _read_cluster_address()
            worker = _connect_remote_driver(address, config, namespace)
            _global_worker = worker
            _start_log_streaming(worker, config)
            # Attached drivers honor profiler_continuous_enabled too —
            # the flag must not be silently ignored off the local-start
            # path.
            from ray_tpu.util import profiler

            profiler.maybe_start_continuous()
            return get_runtime_context()

        node_resources = detect_node_resources(num_cpus, num_tpus, resources)
        node = HeadNode(config, node_resources)
        worker = _connect_driver(node, config, namespace)
        _global_node = node
        _global_worker = worker
        # Live profiling plane: continuous sampler for the head+driver
        # process when configured on (workers start theirs in
        # worker_main; the config rides to them via the env override).
        from ray_tpu.util import profiler

        profiler.maybe_start_continuous()
        _write_cluster_address(f"127.0.0.1:{node.port}")
        _start_log_streaming(worker, config)
        return get_runtime_context()


def _start_log_streaming(worker: CoreWorker, config: Config):
    """Echo worker stdout/stderr at the driver (reference:
    log_monitor.py -> worker prefix lines on the driver's console).
    Every host's tailer publishes on ``worker_logs``; disable with
    config log_to_driver=False or RAY_TPU_LOG_TO_DRIVER=0."""
    if not config.log_to_driver:
        return

    def on_logs(data):
        node = (data.get("node") or "?")[:8]
        for worker_hex, lines in data.get("entries", []):
            for line in lines:
                print(f"(worker={worker_hex} node={node}) {line}")

    try:
        worker.subscribe("worker_logs", on_logs)
    except Exception:
        logger.debug("log streaming unavailable", exc_info=True)


def _read_cluster_address() -> str:
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(ADDRESS_FILE) as f:
            return f.read().strip()
    except FileNotFoundError:
        raise ConnectionError(
            "address='auto' but no running cluster found (no "
            f"{ADDRESS_FILE}); start one with ray_tpu.init() or "
            "`ray-tpu start --head`")


def _write_cluster_address(addr: str):
    try:
        os.makedirs(os.path.dirname(ADDRESS_FILE), exist_ok=True)
        with open(ADDRESS_FILE, "w") as f:
            f.write(addr)
    except OSError:
        pass


def _clear_cluster_address():
    try:
        os.remove(ADDRESS_FILE)
    except OSError:
        pass


def _connect_remote_driver(address: str, config: Config, namespace: str
                           ) -> CoreWorker:
    """Attach to a head in another process over the RPC transport."""
    host, port_s = address.rsplit(":", 1)
    from ray_tpu.core.rpc import EventLoopThread

    loop_thread = EventLoopThread(name="ray-tpu-driver")
    worker_id = WorkerID.from_random()
    cw = CoreWorker(
        config=config,
        loop_thread=loop_thread,
        head=None,
        job_id=JobID.from_int(0),
        worker_id=worker_id,
        mode="driver",
    )
    cw.namespace = namespace

    async def boot():
        await cw.start_server()
        conn = await rpc.connect(host, int(port_s), cw.handlers(),
                                 name="driver-head")
        cw.head = HeadClient(conn=conn)
        return await cw.head.call("register_driver", {
            "host": cw.host, "port": cw.port,
            "worker_id": worker_id.hex(),
        })

    try:
        reply = loop_thread.run(boot(), timeout=30)
    except BaseException:
        # Connection failed: tear down the loop thread and the bound
        # server socket so retries don't leak threads/ports.
        try:
            loop_thread.run(cw.stop(), timeout=5)
        except Exception:
            pass
        loop_thread.stop()
        raise
    cw.job_id = JobID.from_hex(reply["job_id"])
    if reply.get("session_dir"):
        # Spill files must resolve to the cluster's session dir, not a
        # per-process default, or spilled objects are unreadable here.
        os.environ["RAY_TPU_SESSION_DIR"] = reply["session_dir"]
    attached_arena = False
    if reply.get("arena"):
        # Same host as the head: map its arena for zero-copy object IO.
        from ray_tpu.core import native_store

        arena = native_store.NativeArena.attach(reply["arena"])
        if arena is not None:
            native_store.set_attached_arena(arena)
            os.environ["RAY_TPU_ARENA"] = reply["arena"]
            attached_arena = True
    if attached_arena and reply.get("default_node_id"):
        # Sharing the head's store means sharing its node identity.
        cw.node_id_hex = cw.node_id_hex or reply["default_node_id"]
    elif not attached_arena:
        # Different machine (or arena unavailable): this driver has no
        # node store. Big values stay in its in-process memory store and
        # consumers fetch them from the owner over RPC — claiming the
        # head's node id would poison the object directory with
        # locations that don't hold the data.
        cw.no_node_store = True
    from ray_tpu.core.ids import TaskID

    cw._root_task_id = TaskID.for_normal_task(cw.job_id)
    cw._attached_loop_thread = loop_thread
    object_ref_mod.set_core_worker(cw)
    return cw


def _connect_driver(node: HeadNode, config: Config, namespace: str
                    ) -> CoreWorker:
    worker_id = WorkerID.from_random()
    # The driver shares the head's event loop; control-plane calls are
    # direct async dispatch (no socket hop for the in-process head).
    cw = CoreWorker(
        config=config,
        loop_thread=node.loop_thread,
        head=None,
        job_id=JobID.from_int(0),
        worker_id=worker_id,
        mode="driver",
    )
    peer = LocalPeer()
    # In-process driver: its local head calls are accounted per caller
    # kind just like socket peers (util/rpc_stats.py).
    peer.state["caller_kind"] = "driver"

    async def notify_handler(method, payload):
        if method == "pubsub":
            await cw.h_pubsub(peer, payload)

    peer._notify_handler = notify_handler
    cw.head = HeadClient(local_service=node.service, local_peer=peer)
    cw.namespace = namespace

    async def boot():
        await cw.start_server()
        reply = await cw.head.call("register_driver", {
            "host": cw.host, "port": cw.port, "worker_id": worker_id.hex(),
        })
        return reply

    reply = node.loop_thread.run(boot())
    cw.job_id = JobID.from_hex(reply["job_id"])
    # Rebuild the root task id under the real job id.
    from ray_tpu.core.ids import TaskID

    cw._root_task_id = TaskID.for_normal_task(cw.job_id)
    object_ref_mod.set_core_worker(cw)
    return cw


def shutdown():
    global _global_node, _global_worker
    # Cluster-scoped caches in library modules die with the cluster.
    import sys

    col = sys.modules.get("ray_tpu.collective.collective")
    if col is not None:
        col._reset_state()
    with _init_lock:
        cw = object_ref_mod.get_core_worker()
        if cw is not None and _global_node is not None:
            try:
                _global_node.loop_thread.run(cw.stop(), timeout=5)
            except Exception:
                pass
        if cw is not None and _global_node is None:
            # Remote-attached driver: stop its own loop thread.
            lt = getattr(cw, "_attached_loop_thread", None)
            if lt is not None:
                try:
                    lt.run(cw.stop(), timeout=5)
                except Exception:
                    pass
                lt.stop()
        if _global_node is not None:
            _global_node.shutdown()
            _clear_cluster_address()
        object_ref_mod.set_core_worker(None)
        _global_node = None
        _global_worker = None


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return _require_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        timeout: Optional[float] = None, donate: bool = False):
    """Resolve refs to values.

    ``donate=True`` applies to device-plane objects (sharded jax.Arrays
    put through the device-native object plane) pulled from another
    process: once the transfer lands, the serving holder's device
    buffers are released — the get is a move of HBM, not a copy. It is
    a no-op for host-path objects and for same-process (zero-copy)
    hits."""
    cw = _require_worker()
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef, got {type(r)}")
    values = cw.get(ref_list, timeout, donate=donate)
    return values[0] if single else values


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    cw = _require_worker()
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return cw.wait(list(refs), num_returns, timeout, fetch_local)


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    _require_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    _require_worker().cancel_task(ref, force)


def actor_exit():
    """Gracefully exit the current actor (reference: ray.actor.exit_actor)."""
    raise exc.ActorExitSignal()


# ---------------------------------------------------------------------------
# options handling
# ---------------------------------------------------------------------------

_TASK_DEFAULTS = dict(
    num_cpus=1.0, num_tpus=0.0, resources=None, num_returns=1,
    # None = resolve from config (task_default_max_retries) at submit
    # time, so system_config/env overrides reach functions decorated
    # before init().
    max_retries=None, retry_exceptions=False, name="",
    scheduling_strategy=None, runtime_env=None, memory=None,
    # Streaming-generator backpressure: max produced-but-unread chunks
    # before the generator body pauses (0 = unbounded).
    max_queued_stream_chunks=0,
)

_ACTOR_DEFAULTS = dict(
    # max_restarts None = resolve from config
    # (actor_default_max_restarts) at creation time.
    num_cpus=0.0, num_tpus=0.0, resources=None, max_restarts=None,
    max_task_retries=0, max_concurrency=None, name="", namespace="",
    lifetime=None, scheduling_strategy=None, runtime_env=None,
    get_if_exists=False, memory=None,
)


def _build_resources(opts: dict) -> Dict[str, float]:
    out: Dict[str, float] = {}
    if opts.get("num_cpus"):
        out["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        from ray_tpu.core.accelerators import TPUAcceleratorManager

        TPUAcceleratorManager.validate_chip_request(opts["num_tpus"])
        out["TPU"] = float(opts["num_tpus"])
    if opts.get("memory"):
        out["memory"] = float(opts["memory"])
    if opts.get("resources"):
        out.update({k: float(v) for k, v in opts["resources"].items()})
    return out


def _build_strategy(opts: dict):
    strategy = opts.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        return DefaultSchedulingStrategy()
    if strategy == "SPREAD":
        return SpreadSchedulingStrategy()
    if isinstance(strategy, (DefaultSchedulingStrategy,
                             SpreadSchedulingStrategy,
                             NodeAffinitySchedulingStrategy,
                             PlacementGroupSchedulingStrategy)):
        return strategy
    raise ValueError(f"unknown scheduling strategy: {strategy!r}")


# ---------------------------------------------------------------------------
# remote functions
# ---------------------------------------------------------------------------


class RemoteFunction:
    def __init__(self, fn, options: Optional[dict] = None):
        if inspect.iscoroutinefunction(fn):
            raise TypeError(
                "async functions can't be remote tasks; use an async actor"
            )
        self._fn = fn
        self._options = dict(_TASK_DEFAULTS)
        self._options.update(options or {})
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(opts)
        return RemoteFunction(self._fn, merged)

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (reference: ray.dag fn.bind)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        cw = _require_worker()
        opts = self._options
        function_key = cw.export_function(self._fn)
        # Distributed tracing: the active span's context rides a hidden
        # kwarg (reference: tracing_helper's _ray_trace_ctx) so the
        # worker's execution span parents to this submission. Args, not
        # runtime_env — the env is part of the scheduling key and a
        # per-trace env would defeat worker reuse.
        from ray_tpu.util import tracing as _tracing

        if _tracing.is_enabled():
            carrier = _tracing.inject_context()
            if carrier:
                kwargs = dict(kwargs)
                kwargs["_rtpu_trace_ctx"] = carrier
        task_args = cw.serialize_args(args, kwargs)
        n = opts["num_returns"]
        if n == "streaming":
            if not inspect.isgeneratorfunction(self._fn):
                raise TypeError(
                    "num_returns='streaming' requires a generator "
                    "function")
            n = -1  # TaskSpec.STREAMING
        refs = cw.submit_task(
            function_key,
            task_args,
            name=opts["name"] or getattr(self._fn, "__name__", "task"),
            num_returns=n,
            resources=_build_resources(opts),
            max_retries=(0 if n == -1
                         else opts["max_retries"]
                         if opts["max_retries"] is not None
                         else get_config().task_default_max_retries),
            retry_exceptions=opts["retry_exceptions"],
            scheduling_strategy=_build_strategy(opts),
            runtime_env=opts["runtime_env"],
            stream_window=int(opts.get("max_queued_stream_chunks") or 0),
        )
        if n == -1:
            return refs  # an ObjectRefGenerator
        if n == 0:
            return None
        if n == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._fn, '__name__', '?')}' cannot "
            "be called directly; use .remote()"
        )


# ---------------------------------------------------------------------------
# actors
# ---------------------------------------------------------------------------


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1,
                 max_queued_stream_chunks: int = 0):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._max_queued_stream_chunks = max_queued_stream_chunks

    def options(self, num_returns=None,
                max_queued_stream_chunks: Optional[int] = None,
                **_ignored) -> "ActorMethod":
        # None sentinels preserve the method's current settings, so
        # .options(num_returns="streaming").options(
        #     max_queued_stream_chunks=3) composes.
        return ActorMethod(
            self._handle, self._method_name,
            self._num_returns if num_returns is None else num_returns,
            (self._max_queued_stream_chunks
             if max_queued_stream_chunks is None
             else max_queued_stream_chunks))

    def bind(self, *args, **kwargs):
        """Build a lazy actor-method DAG node (reference: ray.dag
        method.bind); compile with node.experimental_compile()."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args,
                               kwargs)

    def remote(self, *args, **kwargs):
        cw = _require_worker()
        n = self._num_returns
        if n == "streaming":
            n = -1  # TaskSpec.STREAMING — the method must return a
            # generator; validated executor-side (the callable lives in
            # the actor's process, not here).
        task_args = cw.serialize_args(args, kwargs)
        refs = cw.submit_actor_task(
            self._handle._actor_id,
            self._method_name,
            task_args,
            num_returns=n,
            stream_window=int(self._max_queued_stream_chunks or 0),
        )
        if n == -1:
            return refs  # an ObjectRefGenerator
        if n == 0:
            return None
        if n == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            "use .remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID,
                 method_meta: Optional[Dict[str, int]] = None):
        self._actor_id = actor_id
        self._method_meta = method_meta or {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name,
                           num_returns=self._method_meta.get(name, 1))

    def __reduce__(self):
        return (_rebuild_actor_handle,
                (self._actor_id.binary(), self._method_meta))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and other._actor_id == self._actor_id)


def _rebuild_actor_handle(actor_id_bytes: bytes,
                          method_meta: Optional[dict] = None) -> ActorHandle:
    return ActorHandle(ActorID(actor_id_bytes), method_meta)


class ActorClass:
    def __init__(self, cls, options: Optional[dict] = None):
        self._cls = cls
        self._options = dict(_ACTOR_DEFAULTS)
        self._options.update(options or {})

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        cw = _require_worker()
        opts = self._options
        if opts.get("get_if_exists") and opts.get("name"):
            try:
                return get_actor(opts["name"],
                                 opts.get("namespace", "") or
                                 getattr(cw, "namespace", ""))
            except ValueError:
                pass
        is_async = _class_is_async(self._cls)
        max_concurrency = opts.get("max_concurrency")
        if max_concurrency is None:
            max_concurrency = 1000 if is_async else 1
        class_key = cw.export_function(self._cls)
        task_args = cw.serialize_args(args, kwargs)
        actor_id = cw.create_actor(
            class_key,
            task_args,
            name=f"{self._cls.__name__}.__init__",
            actor_name=opts.get("name", ""),
            namespace=opts.get("namespace", "") or getattr(cw, "namespace", ""),
            resources=_build_resources(opts),
            max_restarts=(opts["max_restarts"]
                          if opts["max_restarts"] is not None
                          else get_config().actor_default_max_restarts),
            max_task_retries=opts["max_task_retries"],
            max_concurrency=max_concurrency,
            is_async=is_async,
            scheduling_strategy=_build_strategy(opts),
            runtime_env=opts["runtime_env"],
            detached=(opts.get("lifetime") == "detached"),
        )
        # Honor @method(num_returns=N) declarations on the class.
        method_meta = {
            name: getattr(member, "__ray_tpu_num_returns__")
            for name, member in inspect.getmembers(self._cls)
            if hasattr(member, "__ray_tpu_num_returns__")
        }
        return ActorHandle(actor_id, method_meta)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            "directly; use .remote()"
        )


def _class_is_async(cls) -> bool:
    for name, member in inspect.getmembers(cls):
        # __call__ counts: `async def __call__` (the serve token-stream
        # shape) must put the actor on the async executor or its async
        # generator would be rejected by the sync streaming lane.
        if name.startswith("__") and name != "__call__":
            continue
        if (inspect.iscoroutinefunction(member)
                or inspect.isasyncgenfunction(member)):
            return True
    return False


def remote(*args, **options):
    """``@remote`` / ``@remote(**options)`` for functions and classes."""
    if len(args) == 1 and not options and callable(args[0]):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("remote() takes keyword options only")

    def decorator(target):
        if inspect.isclass(target):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    return decorator


def method(num_returns: int = 1):
    """Decorator recording per-method defaults (subset of the reference's
    @ray.method)."""

    def decorator(fn):
        fn.__ray_tpu_num_returns__ = num_returns
        return fn

    return decorator


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("get_named_actor", {
        "name": name,
        "namespace": namespace or getattr(cw, "namespace", ""),
    }))
    if not reply.get("found"):
        raise ValueError(f"named actor {name!r} not found")
    actor_id = ActorID.from_hex(reply["actor_id"])
    cw._on_actor_state_threadsafe(reply)
    return ActorHandle(actor_id)


# ---------------------------------------------------------------------------
# cluster introspection
# ---------------------------------------------------------------------------


def nodes() -> List[dict]:
    cw = _require_worker()
    return cw.loop_thread.run(cw.head.call("get_nodes", {}))


def cluster_resources() -> Dict[str, float]:
    cw = _require_worker()
    return cw.loop_thread.run(cw.head.call("cluster_resources", {}))


def available_resources() -> Dict[str, float]:
    cw = _require_worker()
    return cw.loop_thread.run(cw.head.call("available_resources", {}))


class RuntimeContext:
    def __init__(self, cw: CoreWorker):
        self._cw = cw

    @property
    def job_id(self) -> str:
        return self._cw.job_id.hex()

    @property
    def worker_id(self) -> str:
        return self._cw.worker_id.hex()

    @property
    def current_task_id(self) -> str:
        return self._cw.current_task_id().hex()

    def get_actor_id(self) -> Optional[str]:
        ex = getattr(self._cw, "executor", None)
        if ex is not None and ex.actor_spec is not None:
            return ex.actor_spec.actor_id.hex()
        return None

    @property
    def namespace(self) -> str:
        return getattr(self._cw, "namespace", "")


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_require_worker())


# ---------------------------------------------------------------------------
# placement groups
# ---------------------------------------------------------------------------


class PlacementGroup:
    def __init__(self, pg_id_hex: str):
        self.id_hex = pg_id_hex

    def ready(self, timeout: Optional[float] = None) -> bool:
        cw = _require_worker()
        reply = cw.loop_thread.run(cw.head.call(
            "pg_ready", {"pg_id": self.id_hex, "timeout": timeout}
        ))
        return reply.get("ready", False)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.ready(timeout)

    @property
    def bundle_specs(self) -> List[dict]:
        cw = _require_worker()
        reply = cw.loop_thread.run(cw.head.call("get_pg",
                                                {"pg_id": self.id_hex}))
        return [b["resources"] for b in reply.get("bundles", [])]

    def __reduce__(self):
        return (PlacementGroup, (self.id_hex,))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("create_pg", {
        "bundles": bundles, "strategy": strategy, "name": name,
    }))
    return PlacementGroup(reply["pg_id"])


def remove_placement_group(pg: PlacementGroup):
    cw = _require_worker()
    cw.loop_thread.run(cw.head.call("remove_pg", {"pg_id": pg.id_hex}))


# ---------------------------------------------------------------------------
# internal KV (reference: ray.experimental.internal_kv._internal_kv_*) —
# durable under GCS fault tolerance (persisted write-through to the
# session's sqlite store and reloaded on head restart).
# ---------------------------------------------------------------------------


def kv_put(key: bytes, value: bytes, *, namespace: str = "",
           overwrite: bool = True) -> bool:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("kv_put", {
        "ns": namespace, "key": key, "value": value,
        "overwrite": overwrite,
    }))
    return bool(reply.get("added"))


def kv_get(key: bytes, *, namespace: str = "") -> Optional[bytes]:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("kv_get", {
        "ns": namespace, "key": key,
    }))
    return reply.get("value")


def kv_del(key: bytes, *, namespace: str = "") -> bool:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("kv_del", {
        "ns": namespace, "key": key,
    }))
    return bool(reply.get("deleted"))


def kv_exists(key: bytes, *, namespace: str = "") -> bool:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("kv_exists", {
        "ns": namespace, "key": key,
    }))
    return bool(reply.get("exists"))


def list_named_actors(all_namespaces: bool = False,
                      namespace: str = "") -> list:
    """[{namespace, name}] of live named actors (reference:
    ray.util.list_named_actors)."""
    cw = _require_worker()
    return cw.loop_thread.run(cw.head.call("list_named_actors", {
        "all_namespaces": all_namespaces, "namespace": namespace,
    }))


def kv_keys(prefix: bytes = b"", *, namespace: str = "") -> list:
    cw = _require_worker()
    reply = cw.loop_thread.run(cw.head.call("kv_keys", {
        "ns": namespace, "prefix": prefix,
    }))
    return list(reply.get("keys", []))
