"""Node providers: how the autoscaler acquires/releases capacity.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider ABC) +
_private/fake_multi_node/node_provider.py:237 (FakeMultiNodeProvider,
the in-process provider used to test scaling logic without a cloud).
``TPUPodSliceProvider`` is the TPU-shaped provider contract: create
terminates in whole pod slices (the scheduling gang unit); concrete
GCE/GKE implementations plug in by subclassing and implementing the
two launch hooks.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider contract (reference: node_provider.py)."""

    def create_node(self, node_type: str,
                    resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[dict]:
        """-> [{provider_node_id, node_type, node_id(optional)}]"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Creates logical nodes on the in-process head — the analog of
    RAY_FAKE_CLUSTER=1 (fake_multi_node). Used for autoscaler tests."""

    def __init__(self):
        from ray_tpu import api as _api

        if _api._global_node is None:
            raise RuntimeError(
                "FakeNodeProvider needs an in-process head "
                "(ray_tpu.init() without address=)")
        self._head = _api._global_node
        self._nodes: Dict[str, dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        node_id = self._head.add_node(dict(resources))
        pid = f"fake-{uuid.uuid4().hex[:8]}"
        self._nodes[pid] = {
            "provider_node_id": pid,
            "node_type": node_type,
            "node_id": node_id,
            "created_at": time.time(),
        }
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        info = self._nodes.pop(provider_node_id, None)
        if info is not None:
            self._head.remove_node(info["node_id"])

    def non_terminated_nodes(self) -> List[dict]:
        return list(self._nodes.values())


class TPUPodSliceProvider(NodeProvider):
    """Abstract pod-slice provider: each node type is a slice topology
    (e.g. "v5e-16" = 4 hosts x 4 chips). Subclasses implement the cloud
    calls; the autoscaler logic (slice-granular bin packing) is shared.

    Reference analog: the GCP provider + TPU pod scheduling via the
    synthetic TPU-<ver>-<n>-head resource (_private/accelerators/
    tpu.py:335) — here the slice is a first-class node type.
    """

    #: topology -> (hosts per slice, chips per host)
    TOPOLOGIES = {
        "v4-8": (1, 4),
        "v5e-4": (1, 4),
        "v5e-8": (2, 4),
        "v5e-16": (4, 4),
        "v5e-64": (16, 4),
        "v5p-8": (1, 4),
    }

    def slice_resources(self, topology: str) -> Dict[str, float]:
        hosts, chips = self.TOPOLOGIES[topology]
        return {
            "CPU": 96.0 * hosts,
            "TPU": float(chips * hosts),
            f"TPU-{topology}-head": 1.0,
        }

    def launch_slice(self, topology: str) -> str:
        """Cloud hook: acquire one slice, return its id."""
        raise NotImplementedError

    def release_slice(self, slice_id: str) -> None:
        """Cloud hook: release one slice."""
        raise NotImplementedError

    def create_node(self, node_type: str, resources, labels) -> str:
        return self.launch_slice(node_type)

    def terminate_node(self, provider_node_id: str) -> None:
        self.release_slice(provider_node_id)
