"""Node providers: how the autoscaler acquires/releases capacity.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider ABC) +
_private/fake_multi_node/node_provider.py:237 (FakeMultiNodeProvider,
the in-process provider used to test scaling logic without a cloud).
``TPUPodSliceProvider`` is the TPU-shaped provider contract: create
terminates in whole pod slices (the scheduling gang unit); concrete
GCE/GKE implementations plug in by subclassing and implementing the
two launch hooks.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider contract (reference: node_provider.py)."""

    def create_node(self, node_type: str,
                    resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[dict]:
        """-> [{provider_node_id, node_type, node_id(optional)}]"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Creates logical nodes on the in-process head — the analog of
    RAY_FAKE_CLUSTER=1 (fake_multi_node). Used for autoscaler tests."""

    def __init__(self, head=None):
        if head is None:
            from ray_tpu import api as _api

            if _api._global_node is None:
                raise RuntimeError(
                    "FakeNodeProvider needs an in-process head "
                    "(ray_tpu.init() without address=) or an explicit "
                    "head= (a HeadNode, e.g. from head_main)")
            head = _api._global_node
        self._head = head
        self._nodes: Dict[str, dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        node_id = self._head.add_node(dict(resources))
        pid = f"fake-{uuid.uuid4().hex[:8]}"
        self._nodes[pid] = {
            "provider_node_id": pid,
            "node_type": node_type,
            "node_id": node_id,
            "created_at": time.time(),
        }
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        info = self._nodes.pop(provider_node_id, None)
        if info is not None:
            self._head.remove_node(info["node_id"])

    def non_terminated_nodes(self) -> List[dict]:
        return list(self._nodes.values())


class TPUPodSliceProvider(NodeProvider):
    """Abstract pod-slice provider: each node type is a slice topology
    (e.g. "v5e-16" = 4 hosts x 4 chips). Subclasses implement the cloud
    calls; the autoscaler logic (slice-granular bin packing) is shared.

    Reference analog: the GCP provider + TPU pod scheduling via the
    synthetic TPU-<ver>-<n>-head resource (_private/accelerators/
    tpu.py:335) — here the slice is a first-class node type.
    """

    #: topology -> (hosts per slice, chips per host)
    TOPOLOGIES = {
        "v4-8": (1, 4),
        "v5e-4": (1, 4),
        "v5e-8": (2, 4),
        "v5e-16": (4, 4),
        "v5e-64": (16, 4),
        "v5p-8": (1, 4),
    }

    def slice_resources(self, topology: str) -> Dict[str, float]:
        hosts, chips = self.TOPOLOGIES[topology]
        return {
            "CPU": 96.0 * hosts,
            "TPU": float(chips * hosts),
            f"TPU-{topology}-head": 1.0,
        }

    def launch_slice(self, topology: str) -> str:
        """Cloud hook: acquire one slice, return its id."""
        raise NotImplementedError

    def release_slice(self, slice_id: str) -> None:
        """Cloud hook: release one slice."""
        raise NotImplementedError

    def create_node(self, node_type: str, resources, labels) -> str:
        return self.launch_slice(node_type)

    def terminate_node(self, provider_node_id: str) -> None:
        self.release_slice(provider_node_id)


class GcpTpuPodSliceProvider(TPUPodSliceProvider):
    """Concrete GCE TPU-VM slice provider driving ``gcloud compute tpus
    tpu-vm`` (reference: python/ray/autoscaler/_private/gcp/node_provider
    .py + node.py's GCPTPUNode — that path uses the TPU REST API; the
    CLI carries the same verbs and needs no vendored client).

    Every created VM gets a startup script that runs ``setup_commands``
    (which must make ``ray_tpu`` importable — pip-install a wheel, or
    use a ``runtime_version`` image with it baked in; the stock TPU
    images do NOT ship it) and then launches this framework's node agent
    against ``head_address``, so a slice is schedulable as soon as its
    agents register — the analog of the reference's setup_commands +
    ray-start blocks in cluster YAML.

    ``runner`` injects the command executor (tests pass a recorder; the
    default shells out to gcloud). All calls are synchronous; the
    autoscaler loop already runs provider calls off the event loop.
    """

    def __init__(self, project: str, zone: str, head_address: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "ray-tpu",
                 setup_commands: Optional[List[str]] = None,
                 runner: Optional[Any] = None):
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self.runtime_version = runtime_version
        self.name_prefix = name_prefix
        # E.g. ["pip install https://bucket/ray_tpu.whl"]. Empty means
        # the image already carries the package.
        self.setup_commands = list(setup_commands or [])
        self._run = runner if runner is not None else self._gcloud
        self._slices: Dict[str, dict] = {}
        self._listed_at = 0.0

    @classmethod
    def accelerator_type(cls, topology: str) -> str:
        """gcloud accelerator name for a topology — derived from the
        one TOPOLOGIES table (v5e's marketing name differs
        mechanically) so the two can't drift."""
        if topology not in cls.TOPOLOGIES:
            raise ValueError(f"unknown TPU topology {topology!r}")
        if topology.startswith("v5e-"):
            return "v5litepod-" + topology.split("-", 1)[1]
        return topology

    @staticmethod
    def _gcloud(args: List[str]) -> str:
        import subprocess

        out = subprocess.run(
            ["gcloud"] + args, capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(
                f"gcloud {' '.join(args[:4])}... failed: {out.stderr[-500:]}")
        return out.stdout

    def _startup_script(self) -> str:
        host, port = self.head_address.rsplit(":", 1)
        setup = "\n".join(self.setup_commands)
        return (
            "#! /bin/bash\n"
            + (setup + "\n" if setup else "")
            + "python3 -m ray_tpu.core.node_agent "
            f"--head-host {host} --head-port {port} &\n"
        )

    def launch_slice(self, topology: str) -> str:
        accel = self.accelerator_type(topology)
        name = f"{self.name_prefix}-{topology}-{uuid.uuid4().hex[:8]}"
        # ^:::^ sets a custom metadata delimiter: gcloud splits plain
        # --metadata values on commas, which shell scripts (pip version
        # specs, etc.) routinely contain.
        self._run([
            "compute", "tpus", "tpu-vm", "create", name,
            "--project", self.project, "--zone", self.zone,
            "--accelerator-type", accel,
            "--version", self.runtime_version,
            "--metadata",
            f"^:::^startup-script={self._startup_script()}",
        ])
        self._slices[name] = {
            "provider_node_id": name,
            "node_type": topology,
            "created_at": time.time(),
        }
        return name

    def release_slice(self, slice_id: str) -> None:
        self._run([
            "compute", "tpus", "tpu-vm", "delete", slice_id,
            "--project", self.project, "--zone", self.zone, "--quiet",
        ])
        self._slices.pop(slice_id, None)

    def non_terminated_nodes(self) -> List[dict]:
        """Reconciled against the cloud (10 s TTL): the in-memory dict
        alone would leak slices after a process restart or a create
        call that timed out after the VM actually came up — the
        autoscaler would relaunch while orphans keep billing."""
        now = time.time()
        if now - self._listed_at >= 10.0:
            try:
                out = self._run([
                    "compute", "tpus", "tpu-vm", "list",
                    "--project", self.project, "--zone", self.zone,
                    "--format", "value(name)",
                ])
            except Exception:
                out = None  # cloud unreachable: serve the cached view
            if out is not None:
                live = {}
                for name in out.split():
                    if not name.startswith(self.name_prefix + "-"):
                        continue  # not ours
                    known = self._slices.get(name)
                    if known is None:
                        # Adopted orphan (created before a restart).
                        # name layout: <prefix>-<topology>-<hex8>.
                        topo = name[len(self.name_prefix) + 1:].rsplit(
                            "-", 1)[0]
                        known = {"provider_node_id": name,
                                 "node_type": topo,
                                 "created_at": now}
                    live[name] = known
                self._slices = live
                self._listed_at = now
        return list(self._slices.values())
