"""Autoscaler v2: declarative instance management.

Reference: python/ray/autoscaler/v2/ (instance_manager/,
src/ray/protobuf/autoscaler.proto) — the v2 redesign replaces v1's
imperative launch/kill loop with a DECLARATIVE model: a desired cluster
shape plus per-instance state machines, reconciled every tick, with
explicit instance lifecycles that survive restarts and are inspectable.

Shape here:
- ``ClusterSpec``: desired node-type counts (min/max per type, like the
  v2 proto's ``ClusterResourceConstraint`` + node-type configs).
- ``Instance``: one provider node moving through the v2 lifecycle
  (QUEUED → REQUESTED → ALLOCATED → RUNNING → TERMINATING → TERMINATED).
- ``InstanceManager``: owns instance records, reconciles desired vs
  actual against a NodeProvider, and exposes the state table (the
  ``get_cluster_status`` analog).

The v1 ``StandardAutoscaler`` remains the demand-driven policy; v2 can
wrap it (demand feeds ``ClusterSpec.target``) or run purely declarative
(operator-pinned counts), which is the TPU-slice story: slices are gang
units you declare, not autoscale one worker at a time.
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.providers import NodeProvider

logger = logging.getLogger(__name__)

# v2 instance lifecycle (reference: autoscaler.proto Instance.Status).
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
RUNNING = "RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
FAILED = "ALLOCATION_FAILED"


@dataclass
class NodeTypeSpec:
    name: str
    min_nodes: int = 0
    max_nodes: int = 100
    resources: Dict[str, float] = field(default_factory=dict)


@dataclass
class ClusterSpec:
    """Desired shape: per-type target counts, bounded by min/max."""

    node_types: Dict[str, NodeTypeSpec] = field(default_factory=dict)
    target: Dict[str, int] = field(default_factory=dict)

    def desired(self, node_type: str) -> int:
        spec = self.node_types[node_type]
        want = self.target.get(node_type, spec.min_nodes)
        return max(spec.min_nodes, min(spec.max_nodes, want))


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_node_id: Optional[str] = None
    created_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)
    error: str = ""
    seq: int = 0  # monotonic creation order (created_at can tie)

    def transition(self, status: str, error: str = ""):
        self.status = status
        self.error = error
        self.updated_at = time.time()


class InstanceManager:
    """Declarative reconciler (reference: v2 instance_manager.py +
    reconciler.py): each tick closes the gap between the spec's desired
    counts and live provider nodes via explicit instance records."""

    #: terminal records older than this are pruned (the reference v2
    #: manager similarly GCs terminal instances).
    TERMINAL_RETENTION_S = 600.0

    def __init__(self, spec: ClusterSpec, provider: NodeProvider,
                 max_concurrent_launches: int = 4):
        self.spec = spec
        self.provider = provider
        self.max_concurrent_launches = max_concurrent_launches
        self.instances: Dict[str, Instance] = {}
        self._counter = itertools.count()

    def _new_instance(self, node_type: str, **kw) -> Instance:
        seq = next(self._counter)
        inst = Instance(f"inst-{seq}", node_type, seq=seq, **kw)
        self.instances[inst.instance_id] = inst
        return inst

    # -- introspection (get_cluster_status analog) ---------------------

    def cluster_status(self) -> dict:
        by_status: Dict[str, int] = {}
        for inst in self.instances.values():
            by_status[inst.status] = by_status.get(inst.status, 0) + 1
        return {
            "instances": [vars(i).copy()
                          for i in self.instances.values()],
            "by_status": by_status,
            "desired": {t: self.spec.desired(t)
                        for t in self.spec.node_types},
        }

    # -- declarative input ---------------------------------------------

    def scale(self, node_type: str, count: int):
        """Declare the desired count (clamped to min/max at reconcile)."""
        if node_type not in self.spec.node_types:
            raise ValueError(f"unknown node type {node_type!r}")
        self.spec.target[node_type] = count

    # -- reconciliation ------------------------------------------------

    def reconcile(self) -> dict:
        """One tick: sync records with the provider, then launch or
        terminate toward the desired counts. Returns the action summary."""
        self._sync_with_provider()
        launched: Dict[str, int] = {}
        terminated: List[str] = []
        # Reconcile every type we have a spec OR live instances for —
        # adopted nodes of types dropped from the spec must converge to
        # zero, not linger unmanaged.
        all_types = set(self.spec.node_types) | {
            i.node_type for i in self.instances.values()
            if i.status in (QUEUED, REQUESTED, RUNNING)}
        for node_type in all_types:
            live = [i for i in self.instances.values()
                    if i.node_type == node_type
                    and i.status in (QUEUED, REQUESTED, RUNNING)]
            desired = (self.spec.desired(node_type)
                       if node_type in self.spec.node_types else 0)
            gap = desired - len(live)
            if gap > 0:
                for _ in range(gap):
                    self._new_instance(node_type)
            elif gap < 0:
                need = -gap
                # Cancel queued launches FIRST (free), then terminate
                # running nodes newest-first (least sunk state; seq
                # breaks created_at ties deterministically).
                for inst in [i for i in live if i.status == QUEUED][:need]:
                    inst.transition(TERMINATED, error="cancelled")
                    terminated.append(inst.instance_id)
                    need -= 1
                victims = sorted(
                    (i for i in live if i.status == RUNNING),
                    key=lambda i: (-i.created_at, -i.seq))[:need]
                for inst in victims:
                    inst.transition(TERMINATING)
        # Drive QUEUED → launch, capping ATTEMPTS per tick (a failing
        # provider must not absorb an unbounded number of create calls).
        attempts = 0
        for inst in list(self.instances.values()):
            if inst.status != QUEUED:
                continue
            if attempts >= self.max_concurrent_launches:
                break
            attempts += 1
            inst.transition(REQUESTED)
            resources = (self.spec.node_types[inst.node_type].resources
                         if inst.node_type in self.spec.node_types
                         else {})
            try:
                node_id = self.provider.create_node(
                    inst.node_type, resources, {})
                inst.provider_node_id = node_id
                inst.transition(RUNNING)
                launched[inst.node_type] = (
                    launched.get(inst.node_type, 0) + 1)
            except Exception as e:
                inst.transition(FAILED, error=str(e))
                logger.warning("launch of %s failed: %s",
                               inst.node_type, e)
        # Drive TERMINATING → TERMINATED.
        live_pids = {n["provider_node_id"]
                     for n in self.provider.non_terminated_nodes()}
        for inst in self.instances.values():
            if inst.status != TERMINATING:
                continue
            if (inst.provider_node_id is None
                    or inst.provider_node_id not in live_pids):
                # Already gone (preempted / raced): converge instead of
                # retrying a terminate that can never succeed.
                inst.transition(TERMINATED)
                terminated.append(inst.instance_id)
                continue
            try:
                self.provider.terminate_node(inst.provider_node_id)
                inst.transition(TERMINATED)
                terminated.append(inst.instance_id)
            except Exception as e:
                logger.warning("terminate of %s failed: %s",
                               inst.instance_id, e)
        self._prune_terminal()
        return {"launched": launched, "terminated": terminated}

    def _prune_terminal(self):
        cutoff = time.time() - self.TERMINAL_RETENTION_S
        for iid in [i.instance_id for i in self.instances.values()
                    if i.status in (TERMINATED, FAILED)
                    and i.updated_at < cutoff]:
            self.instances.pop(iid, None)

    def _sync_with_provider(self):
        """Adopt provider nodes with no record (restart recovery) and
        mark records whose nodes vanished (crashed/preempted) so the
        next pass relaunches toward the desired count."""
        live_ids = {n["provider_node_id"]: n
                    for n in self.provider.non_terminated_nodes()}
        known = {i.provider_node_id for i in self.instances.values()
                 if i.provider_node_id}
        for pid, node in live_ids.items():
            if pid not in known:
                self._new_instance(node["node_type"], status=RUNNING,
                                   provider_node_id=pid)
        for inst in self.instances.values():
            if (inst.status == RUNNING
                    and inst.provider_node_id not in live_ids):
                inst.transition(TERMINATED,
                                error="node vanished (preempted?)")
