"""Autoscaler v2: declarative instance management.

Reference: python/ray/autoscaler/v2/ (instance_manager/,
src/ray/protobuf/autoscaler.proto) — the v2 redesign replaces v1's
imperative launch/kill loop with a DECLARATIVE model: a desired cluster
shape plus per-instance state machines, reconciled every tick, with
explicit instance lifecycles that survive restarts and are inspectable.

Shape here:
- ``ClusterSpec``: desired node-type counts (min/max per type, like the
  v2 proto's ``ClusterResourceConstraint`` + node-type configs).
- ``Instance``: one provider node moving through the v2 lifecycle
  (QUEUED → REQUESTED → ALLOCATED → RUNNING → TERMINATING → TERMINATED).
- ``InstanceManager``: owns instance records, reconciles desired vs
  actual against a NodeProvider, and exposes the state table (the
  ``get_cluster_status`` analog).

The v1 ``StandardAutoscaler`` remains the demand-driven policy; v2 can
wrap it (demand feeds ``ClusterSpec.target``) or run purely declarative
(operator-pinned counts), which is the TPU-slice story: slices are gang
units you declare, not autoscale one worker at a time.
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.providers import NodeProvider

logger = logging.getLogger(__name__)

# v2 instance lifecycle (reference: autoscaler.proto Instance.Status).
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
RUNNING = "RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
FAILED = "ALLOCATION_FAILED"


@dataclass
class NodeTypeSpec:
    name: str
    min_nodes: int = 0
    max_nodes: int = 100
    resources: Dict[str, float] = field(default_factory=dict)


@dataclass
class ClusterSpec:
    """Desired shape: per-type target counts, bounded by min/max."""

    node_types: Dict[str, NodeTypeSpec] = field(default_factory=dict)
    target: Dict[str, int] = field(default_factory=dict)

    def desired(self, node_type: str) -> int:
        spec = self.node_types[node_type]
        want = self.target.get(node_type, spec.min_nodes)
        return max(spec.min_nodes, min(spec.max_nodes, want))


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_node_id: Optional[str] = None
    created_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)
    error: str = ""
    seq: int = 0  # monotonic creation order (created_at can tie)

    def transition(self, status: str, error: str = ""):
        self.status = status
        self.error = error
        self.updated_at = time.time()


class InstanceManager:
    """Declarative reconciler (reference: v2 instance_manager.py +
    reconciler.py): each tick closes the gap between the spec's desired
    counts and live provider nodes via explicit instance records."""

    #: terminal records older than this are pruned (the reference v2
    #: manager similarly GCs terminal instances).
    TERMINAL_RETENTION_S = 600.0

    def __init__(self, spec: ClusterSpec, provider: NodeProvider,
                 max_concurrent_launches: int = 4,
                 launch_mode: str = "sync"):
        """``launch_mode="async"`` runs provider create/terminate calls
        on a background thread pool so one slow cloud call (gcloud create
        can take minutes) never stalls the reconcile tick — the mode the
        Monitor uses (reference: v1 launches from NodeLauncher threads).
        ``"sync"`` keeps the deterministic inline behavior for
        single-shot/declarative use."""
        if launch_mode not in ("sync", "async"):
            raise ValueError(f"launch_mode {launch_mode!r}")
        self.spec = spec
        self.provider = provider
        self.max_concurrent_launches = max_concurrent_launches
        self.instances: Dict[str, Instance] = {}
        self._counter = itertools.count()
        self._pool = None
        self._launches: Dict[str, object] = {}  # instance_id -> Future
        self._terminations: Dict[str, object] = {}  # instance_id -> Future
        if launch_mode == "async":
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=max(1, max_concurrent_launches),
                thread_name_prefix="rtpu-launch")

    def _new_instance(self, node_type: str, **kw) -> Instance:
        seq = next(self._counter)
        inst = Instance(f"inst-{seq}", node_type, seq=seq, **kw)
        self.instances[inst.instance_id] = inst
        return inst

    # -- introspection (get_cluster_status analog) ---------------------

    def cluster_status(self) -> dict:
        # Snapshot first: status is read from other threads (the head's
        # h_autoscaler_status) while the Monitor thread reconciles.
        instances = list(self.instances.values())
        by_status: Dict[str, int] = {}
        for inst in instances:
            by_status[inst.status] = by_status.get(inst.status, 0) + 1
        return {
            "instances": [vars(i).copy() for i in instances],
            "by_status": by_status,
            "desired": {t: self.spec.desired(t)
                        for t in self.spec.node_types},
        }

    # -- declarative input ---------------------------------------------

    def scale(self, node_type: str, count: int):
        """Declare the desired count (clamped to min/max at reconcile)."""
        if node_type not in self.spec.node_types:
            raise ValueError(f"unknown node type {node_type!r}")
        self.spec.target[node_type] = count

    def terminate_node(self, provider_node_id: str) -> bool:
        """Mark the instance backing a specific provider node for
        termination (the Monitor's idle-node path picks victims by id —
        reference: StandardAutoscaler terminating specific idle nodes,
        not newest-first)."""
        for inst in self.instances.values():
            if (inst.provider_node_id == provider_node_id
                    and inst.status in (RUNNING, REQUESTED)):
                inst.transition(TERMINATING)
                return True
        return False

    # -- reconciliation ------------------------------------------------

    def reconcile(self) -> dict:
        """One tick: sync records with the provider, then launch or
        terminate toward the desired counts. Returns the action summary.
        In async mode the tick never blocks on the cloud: creates run on
        the pool and are harvested on later ticks."""
        launched_async = self._harvest_launches()
        self._sync_with_provider()
        launched: Dict[str, int] = {}
        terminated: List[str] = []
        # Reconcile every type we have a spec OR live instances for —
        # adopted nodes of types dropped from the spec must converge to
        # zero, not linger unmanaged.
        all_types = set(self.spec.node_types) | {
            i.node_type for i in self.instances.values()
            if i.status in (QUEUED, REQUESTED, RUNNING)}
        for node_type in all_types:
            live = [i for i in self.instances.values()
                    if i.node_type == node_type
                    and i.status in (QUEUED, REQUESTED, RUNNING)]
            desired = (self.spec.desired(node_type)
                       if node_type in self.spec.node_types else 0)
            gap = desired - len(live)
            if gap > 0:
                for _ in range(gap):
                    self._new_instance(node_type)
            elif gap < 0:
                need = -gap
                # Cancel queued launches FIRST (free), then terminate
                # running nodes newest-first (least sunk state; seq
                # breaks created_at ties deterministically).
                for inst in [i for i in live if i.status == QUEUED][:need]:
                    inst.transition(TERMINATED, error="cancelled")
                    terminated.append(inst.instance_id)
                    need -= 1
                # In-flight creates next: flipping them off REQUESTED
                # makes the harvest release the node on arrival.
                for inst in [i for i in live
                             if i.status == REQUESTED][:max(0, need)]:
                    inst.transition(TERMINATED,
                                    error="cancelled mid-launch")
                    terminated.append(inst.instance_id)
                    need -= 1
                victims = sorted(
                    (i for i in live if i.status == RUNNING),
                    key=lambda i: (-i.created_at, -i.seq))[:need]
                for inst in victims:
                    inst.transition(TERMINATING)
        # Drive QUEUED → launch, capping ATTEMPTS per tick (a failing
        # provider must not absorb an unbounded number of create calls).
        attempts = len(self._launches)
        for inst in list(self.instances.values()):
            if inst.status != QUEUED:
                continue
            if attempts >= self.max_concurrent_launches:
                break
            attempts += 1
            inst.transition(REQUESTED)
            resources = (self.spec.node_types[inst.node_type].resources
                         if inst.node_type in self.spec.node_types
                         else {})
            if self._pool is not None:
                self._launches[inst.instance_id] = self._pool.submit(
                    self.provider.create_node, inst.node_type,
                    resources, {})
                continue
            try:
                node_id = self.provider.create_node(
                    inst.node_type, resources, {})
                inst.provider_node_id = node_id
                inst.transition(RUNNING)
                launched[inst.node_type] = (
                    launched.get(inst.node_type, 0) + 1)
            except Exception as e:
                inst.transition(FAILED, error=str(e))
                logger.warning("launch of %s failed: %s",
                               inst.node_type, e)
        for node_type, n in launched_async.items():
            launched[node_type] = launched.get(node_type, 0) + n
        # Drive TERMINATING → TERMINATED.
        live_pids = {n["provider_node_id"]
                     for n in self.provider.non_terminated_nodes()}
        for inst in self.instances.values():
            if inst.status != TERMINATING:
                continue
            if (inst.provider_node_id is None
                    or inst.provider_node_id not in live_pids):
                # Already gone (preempted / raced): converge instead of
                # retrying a terminate that can never succeed.
                inst.transition(TERMINATED)
                terminated.append(inst.instance_id)
                continue
            if self._pool is not None:
                if inst.instance_id not in self._terminations:
                    self._terminations[inst.instance_id] = \
                        self._pool.submit(self.provider.terminate_node,
                                          inst.provider_node_id)
                continue
            try:
                self.provider.terminate_node(inst.provider_node_id)
                inst.transition(TERMINATED)
                terminated.append(inst.instance_id)
            except Exception as e:
                logger.warning("terminate of %s failed: %s",
                               inst.instance_id, e)
        terminated.extend(self._harvest_terminations())
        self._prune_terminal()
        return {"launched": launched, "terminated": terminated}

    def _harvest_launches(self) -> Dict[str, int]:
        """Collect finished async creates (REQUESTED → RUNNING/FAILED).
        A launch whose instance was cancelled mid-flight gets its node
        released again — never leak a billing slice."""
        done: Dict[str, int] = {}
        for iid, fut in list(self._launches.items()):
            if not fut.done():
                continue
            del self._launches[iid]
            inst = self.instances.get(iid)
            try:
                node_id = fut.result()
            except Exception as e:  # noqa: BLE001
                if inst is not None and inst.status == REQUESTED:
                    inst.transition(FAILED, error=str(e))
                logger.warning("async launch failed: %s", e)
                continue
            if inst is None or inst.status != REQUESTED:
                # Scaled down while the create was in flight: record the
                # orphan as TERMINATING so the normal termination driver
                # owns (and retries) its release — a bare fire-and-forget
                # terminate could leak a billing slice on one transient
                # cloud error.
                self._new_instance(
                    inst.node_type if inst is not None else "adopted",
                    status=TERMINATING, provider_node_id=node_id,
                    error="cancelled mid-launch; releasing")
                continue
            inst.provider_node_id = node_id
            inst.transition(RUNNING)
            done[inst.node_type] = done.get(inst.node_type, 0) + 1
        return done

    def _harvest_terminations(self) -> List[str]:
        out: List[str] = []
        for iid, fut in list(self._terminations.items()):
            if not fut.done():
                continue
            del self._terminations[iid]
            inst = self.instances.get(iid)
            if inst is None:
                continue
            try:
                fut.result()
                inst.transition(TERMINATED)
                out.append(iid)
            except Exception as e:  # noqa: BLE001
                logger.warning("terminate of %s failed: %s", iid, e)
                # stays TERMINATING; retried next tick
        return out

    def _prune_terminal(self):
        cutoff = time.time() - self.TERMINAL_RETENTION_S
        for iid in [i.instance_id for i in self.instances.values()
                    if i.status in (TERMINATED, FAILED)
                    and i.updated_at < cutoff]:
            self.instances.pop(iid, None)

    def _sync_with_provider(self):
        """Adopt provider nodes with no record (restart recovery) and
        mark records whose nodes vanished (crashed/preempted) so the
        next pass relaunches toward the desired count."""
        live_ids = {n["provider_node_id"]: n
                    for n in self.provider.non_terminated_nodes()}
        known = {i.provider_node_id for i in self.instances.values()
                 if i.provider_node_id}
        # Adoption is deferred while async creates are outstanding: a
        # node the provider already lists but whose create-future hasn't
        # been harvested would otherwise be double-recorded.
        if not self._launches:
            for pid, node in live_ids.items():
                if pid not in known:
                    self._new_instance(node["node_type"], status=RUNNING,
                                       provider_node_id=pid)
        for inst in self.instances.values():
            if (inst.status == RUNNING
                    and inst.provider_node_id not in live_ids):
                inst.transition(TERMINATED,
                                error="node vanished (preempted?)")
