"""StandardAutoscaler: demand-driven scaling loop.

Reference: autoscaler/_private/autoscaler.py:171 (StandardAutoscaler,
update :373) + resource_demand_scheduler.py (bin-packing pending demand
onto node types) + monitor.py (the loop reading load from the GCS).
Each update(): read pending demand + node utilization from the head,
bin-pack unmet demand onto configured node types, launch up to the
per-type max, and terminate nodes idle beyond the timeout (respecting
min_workers).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.providers import NodeProvider

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeType:
    """Reference: cluster YAML available_node_types entries."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: List[NodeType]
    idle_timeout_s: float = 60.0
    upscaling_speed: float = 1.0  # max new nodes per update, as a
    # fraction of current count (>=1 node always allowed)


def _fits(demand: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in demand.items())


def _subtract(capacity: Dict[str, float], demand: Dict[str, float]):
    for k, v in demand.items():
        capacity[k] = capacity.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        self.config = config
        self.provider = provider
        self._idle_since: Dict[str, float] = {}
        self._launched_by_type: Dict[str, int] = {}

    # -- load ----------------------------------------------------------
    def _get_load(self) -> dict:
        from ray_tpu.core.object_ref import get_core_worker

        cw = get_core_worker()
        if cw is None:
            raise RuntimeError("ray_tpu not initialized")
        return cw.loop_thread.run(cw.head.call("get_load", {}))

    # -- planning ------------------------------------------------------
    def plan(self, load: dict,
             extra_capacity: Optional[List[Dict[str, float]]] = None,
             pending_by_type: Optional[Dict[str, int]] = None
             ) -> tuple:
        """Pure planning: (to_launch: {type: n}, to_terminate: [ids]).

        ``extra_capacity``: hypothetical availability for nodes that are
        coming but not yet ALIVE (async launches in flight, booting
        provider nodes) — the Monitor passes these so a booting node
        isn't re-launched every tick. ``pending_by_type``: in-flight
        launches that are not provider nodes yet, counted toward the
        min_workers floor and max_workers caps for the same reason."""
        provider_nodes = self.provider.non_terminated_nodes()
        counts: Dict[str, int] = {}
        for n in provider_nodes:
            counts[n["node_type"]] = counts.get(n["node_type"], 0) + 1
        for tname, n in (pending_by_type or {}).items():
            counts[tname] = counts.get(tname, 0) + n

        # Unmet demand: pending shapes that no ALIVE node's availability
        # covers (simulate packing onto current availability first).
        avail = [dict(n["available"]) for n in load["nodes"]
                 if n["state"] == "ALIVE"]
        avail.extend(dict(c) for c in (extra_capacity or []))
        unmet: List[Dict[str, float]] = []
        for demand in load["pending"]:
            placed = False
            for cap in avail:
                if _fits(demand, cap):
                    _subtract(cap, demand)
                    placed = True
                    break
            if not placed:
                unmet.append(demand)

        # Bin-pack unmet demand onto hypothetical new nodes by type
        # (first type that fits each shape; reference: the demand
        # scheduler's utilization-score packing, simplified).
        to_launch: Dict[str, int] = {}
        new_caps: List[tuple] = []  # (type_name, remaining capacity)
        for demand in unmet:
            placed = False
            for tname, cap in new_caps:
                if _fits(demand, cap):
                    _subtract(cap, demand)
                    placed = True
                    break
            if placed:
                continue
            for nt in self.config.node_types:
                current = counts.get(nt.name, 0) + to_launch.get(nt.name, 0)
                if current >= nt.max_workers:
                    continue
                if _fits(demand, dict(nt.resources)):
                    cap = dict(nt.resources)
                    _subtract(cap, demand)
                    new_caps.append((nt.name, cap))
                    to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                    placed = True
                    break
            if not placed:
                logger.warning("demand %s fits no node type", demand)

        # min_workers floor.
        for nt in self.config.node_types:
            have = counts.get(nt.name, 0) + to_launch.get(nt.name, 0)
            if have < nt.min_workers:
                to_launch[nt.name] = (to_launch.get(nt.name, 0)
                                      + nt.min_workers - have)

        # Upscaling speed cap.
        total = sum(counts.values()) or 1
        cap_new = max(1, int(total * self.config.upscaling_speed))
        budget = cap_new
        for tname in list(to_launch):
            take = min(to_launch[tname], budget)
            budget -= take
            if take == 0:
                del to_launch[tname]
            else:
                to_launch[tname] = take

        # Idle termination: provider nodes whose head node has no active
        # leases and full availability, idle past the timeout, above
        # min_workers.
        now = time.time()
        by_node_id = {n.get("node_id"): n for n in provider_nodes
                      if n.get("node_id") is not None}
        to_terminate: List[str] = []
        idle_by_type: Dict[str, List[str]] = {}
        for ln in load["nodes"]:
            from ray_tpu.core.ids import NodeID

            node_id = NodeID.from_hex(ln["node_id"])
            pnode = by_node_id.get(node_id)
            if pnode is None or ln["state"] != "ALIVE":
                continue
            busy = ln["active_leases"] > 0
            pid = pnode["provider_node_id"]
            if busy:
                self._idle_since.pop(pid, None)
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            if now - first_idle >= self.config.idle_timeout_s:
                idle_by_type.setdefault(pnode["node_type"], []).append(pid)
        for nt in self.config.node_types:
            idle = idle_by_type.get(nt.name, [])
            keep = max(0, nt.min_workers - (counts.get(nt.name, 0)
                                            - len(idle)))
            removable = idle[:len(idle) - keep] if keep else idle
            to_terminate.extend(removable)
        return to_launch, to_terminate

    # -- acting --------------------------------------------------------
    def update(self) -> dict:
        load = self._get_load()
        to_launch, to_terminate = self.plan(load)
        launched = []
        for tname, n in to_launch.items():
            nt = next(t for t in self.config.node_types
                      if t.name == tname)
            for _ in range(n):
                launched.append(self.provider.create_node(
                    tname, nt.resources, nt.labels))
        for pid in to_terminate:
            self._idle_since.pop(pid, None)
            self.provider.terminate_node(pid)
        return {"launched": launched, "terminated": to_terminate,
                "pending_demand": len(load["pending"])}
