"""ray_tpu.autoscaler — declarative cluster scaling.

Reference capability: python/ray/autoscaler (StandardAutoscaler v1 +
the v2 declarative instance manager / GcsAutoscalerStateManager). The
TPU-first delta: node types are pod-slice shaped — a node type carries
whole-slice resources and scaling acquires/releases slices as units
(gang-granular failure and scaling domains).
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    NodeType,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.providers import (
    FakeNodeProvider,
    NodeProvider,
    GcpTpuPodSliceProvider,
    TPUPodSliceProvider,
)

__all__ = [
    "AutoscalerConfig",
    "FakeNodeProvider",
    "NodeProvider",
    "NodeType",
    "StandardAutoscaler",
    "GcpTpuPodSliceProvider",
    "TPUPodSliceProvider",
]

from ray_tpu.autoscaler.v2 import (  # noqa: E402
    ClusterSpec,
    Instance,
    InstanceManager,
    NodeTypeSpec,
)

__all__ += ["ClusterSpec", "Instance", "InstanceManager", "NodeTypeSpec"]
