"""Autoscaler monitor: the head-side loop that actually runs scaling.

Reference: python/ray/autoscaler/_private/monitor.py:126 — the Monitor
head-node process whose loop (:360) reads load from the GCS (:241) and
drives StandardAutoscaler.update(). Here the same loop drives the v1
demand policy (``StandardAutoscaler.plan`` — bin-packing pending demand
onto node types, idle termination) through the v2 ``InstanceManager``
(declarative records, ASYNC provider calls), so one slow cloud create
never stalls a tick.

Runs either embedded in the head process (``HeadNode`` starts it when
``RAY_TPU_AUTOSCALER=1``, config from ``RAY_TPU_AUTOSCALER_CONFIG``) or
in-driver for tests/tools (``Monitor(...).start()`` with a
FakeNodeProvider).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, NodeType, StandardAutoscaler
from ray_tpu.autoscaler.providers import NodeProvider
from ray_tpu.autoscaler.v2 import (
    QUEUED,
    REQUESTED,
    RUNNING,
    TERMINATING,
    ClusterSpec,
    InstanceManager,
    NodeTypeSpec,
)

logger = logging.getLogger(__name__)


class Monitor:
    """v1 policy × v2 lifecycle, on a timer."""

    #: How long a created node without head-node linkage is presumed to
    #: still be booting (counts as coming capacity). TPU slices take
    #: minutes to provision.
    BOOT_GRACE_S = 300.0

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 load_fn: Callable[[], dict], interval_s: float = 5.0,
                 max_concurrent_launches: int = 4,
                 launch_mode: str = "async"):
        self.config = config
        self.provider = provider
        self.load_fn = load_fn
        self.interval_s = interval_s
        self.policy = StandardAutoscaler(config, provider)
        spec = ClusterSpec(node_types={
            nt.name: NodeTypeSpec(nt.name, nt.min_workers, nt.max_workers,
                                  dict(nt.resources))
            for nt in config.node_types
        })
        self.im = InstanceManager(
            spec, provider,
            max_concurrent_launches=max_concurrent_launches,
            launch_mode=launch_mode)
        self.last_summary: dict = {}
        self.last_error: str = ""
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one tick ------------------------------------------------------

    def tick(self) -> dict:
        load = self.load_fn()
        # Capacity that is COMING but not yet schedulable (async create
        # in flight, or created node booting toward its head
        # registration) must count against demand, or every tick while a
        # node boots would launch another (the v1 monitor tracks this as
        # pending launches + non-ALIVE provider nodes). Counted from
        # INSTANCE records only — a mid-create node that the provider
        # already lists has exactly one record (REQUESTED), so it can't
        # be double-counted against both views.
        alive_node_ids = {n["node_id"] for n in load["nodes"]
                          if n["state"] == "ALIVE"}
        provider_by_pid = {n["provider_node_id"]: n
                           for n in self.provider.non_terminated_nodes()}
        pending_caps: List[Dict[str, float]] = []
        pending_by_type: Dict[str, int] = {}
        types = {nt.name: nt for nt in self.config.node_types}
        booting = 0
        now = time.time()
        for inst in list(self.im.instances.values()):
            if inst.node_type not in types:
                continue
            if inst.status in (QUEUED, REQUESTED):
                coming = True
            elif inst.status == RUNNING:
                # Created but possibly still booting toward its head
                # registration. Providers that expose the head node_id
                # (FakeNodeProvider) answer exactly; otherwise fall back
                # to a boot-grace window on instance age.
                pnode = provider_by_pid.get(inst.provider_node_id, {})
                nid = pnode.get("node_id")
                nid_hex = nid.hex() if hasattr(nid, "hex") \
                    and nid is not None else nid
                if nid_hex is not None:
                    coming = nid_hex not in alive_node_ids
                else:
                    coming = now - inst.created_at < self.BOOT_GRACE_S
                if coming:
                    booting += 1
            else:
                continue
            if coming:
                pending_caps.append(dict(types[inst.node_type].resources))
                # Floor/cap counting: only instances that are NOT yet
                # provider nodes — RUNNING-booting ones already appear
                # in plan()'s provider counts.
                if inst.status in (QUEUED, REQUESTED):
                    pending_by_type[inst.node_type] = \
                        pending_by_type.get(inst.node_type, 0) + 1
        to_launch, to_terminate = self.policy.plan(
            load, extra_capacity=pending_caps,
            pending_by_type=pending_by_type)
        # Specific idle victims first (the policy picked THEM, not
        # newest-first), then declare per-type targets and reconcile.
        for pid in to_terminate:
            self.im.terminate_node(pid)
        current: Dict[str, int] = {}
        for inst in self.im.instances.values():
            if inst.status in (QUEUED, REQUESTED, RUNNING):
                current[inst.node_type] = current.get(inst.node_type,
                                                      0) + 1
        for tname in types:
            self.im.scale(tname, current.get(tname, 0)
                          + to_launch.get(tname, 0))
        summary = self.im.reconcile()
        self.ticks += 1
        self.last_summary = {
            "tick": self.ticks,
            "ts": time.time(),
            "pending_demand": len(load["pending"]),
            "booting": booting,
            "planned_launches": to_launch,
            "planned_terminations": list(to_terminate),
            **summary,
        }
        return self.last_summary

    # -- loop ----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="rtpu-autoscaler", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
                self.last_error = ""
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self.last_error = f"{type(e).__name__}: {e}"
                logger.exception("autoscaler tick failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def status(self) -> dict:
        """CLI / dashboard surface (``ray status`` analog)."""
        return {
            "running": self._thread is not None and
            self._thread.is_alive(),
            "interval_s": self.interval_s,
            "last_summary": self.last_summary,
            "last_error": self.last_error,
            "cluster": self.im.cluster_status(),
        }


def monitor_from_config_file(path: str, provider: NodeProvider,
                             load_fn, **kw) -> Monitor:
    """Build a Monitor from a cluster-config JSON (the cluster-YAML
    analog): {"node_types": [{"name", "resources", "min_workers",
    "max_workers"}], "idle_timeout_s": 60}."""
    with open(path) as f:
        raw = json.load(f)
    config = AutoscalerConfig(
        node_types=[NodeType(
            name=t["name"], resources=t["resources"],
            min_workers=t.get("min_workers", 0),
            max_workers=t.get("max_workers", 10),
            labels=t.get("labels", {}),
        ) for t in raw["node_types"]],
        idle_timeout_s=raw.get("idle_timeout_s", 60.0),
        upscaling_speed=raw.get("upscaling_speed", 1.0),
    )
    return Monitor(config, provider, load_fn,
                   interval_s=raw.get("interval_s", 5.0), **kw)


def provider_from_config(raw: dict, head_address: str,
                         head_node=None) -> NodeProvider:
    """Instantiate the provider named in the cluster config."""
    ptype = raw.get("provider", {}).get("type", "fake")
    if ptype == "fake":
        from ray_tpu.autoscaler.providers import FakeNodeProvider

        return FakeNodeProvider(head=head_node)
    if ptype == "gcp_tpu":
        from ray_tpu.autoscaler.providers import GcpTpuPodSliceProvider

        p = raw["provider"]
        return GcpTpuPodSliceProvider(
            project=p["project"], zone=p["zone"],
            head_address=p.get("head_address", head_address),
            runtime_version=p.get("runtime_version",
                                  "tpu-ubuntu2204-base"),
            name_prefix=p.get("name_prefix", "ray-tpu"),
            setup_commands=p.get("setup_commands"),
        )
    raise ValueError(f"unknown provider type {ptype!r}")
