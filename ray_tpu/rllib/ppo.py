"""PPO (reference: rllib/algorithms/ppo/ppo.py:379, training_step :405,
ppo_learner.py loss).

training_step: parallel env-runner sampling -> GAE -> minibatched
clipped-surrogate SGD on the learner -> weight broadcast. The loss and
GAE are jit-compiled; sampling runs on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.math import compute_gae, explained_variance


def ppo_loss(fwd, batch, *, clip_param: float = 0.2,
             vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
             vf_clip_param: float = 10.0):
    """Clipped surrogate objective (reference: ppo_learner compute_loss)."""
    out = fwd(batch["obs"])
    logits = out["logits"]
    logp_all = jax.nn.log_softmax(logits)
    idx = jnp.arange(logits.shape[0])
    logp = logp_all[idx, batch["actions"]]
    ratio = jnp.exp(logp - batch["logp"])
    adv = batch["advantages"]
    surr1 = ratio * adv
    surr2 = jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
    pi_loss = -jnp.mean(jnp.minimum(surr1, surr2))
    vf_err = jnp.clip((out["vf"] - batch["targets"]) ** 2,
                      0.0, vf_clip_param ** 2)
    vf_loss = jnp.mean(vf_err)
    entropy = -jnp.mean(
        jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    aux = {
        "policy_loss": pi_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "kl": jnp.mean(batch["logp"] - logp),
        "vf_explained_var": explained_variance(batch["targets"],
                                               out["vf"]),
    }
    return total, aux


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vf_clip_param = 10.0
        self.num_epochs = 4
        self.minibatch_size = 256
        self.lam = 0.95
        self.algo_class = PPO

    def training(self, *, clip_param=None, vf_coeff=None,
                 entropy_coeff=None, num_epochs=None, minibatch_size=None,
                 lam=None, vf_clip_param=None, **kwargs) -> "PPOConfig":
        super().training(**kwargs)
        for name, val in [("clip_param", clip_param),
                          ("vf_coeff", vf_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("num_epochs", num_epochs),
                          ("minibatch_size", minibatch_size),
                          ("lam", lam),
                          ("vf_clip_param", vf_clip_param)]:
            if val is not None:
                setattr(self, name, val)
        return self


class PPO(Algorithm):
    config_class = PPOConfig

    def _build(self):
        cfg = self.config
        self._build_common(ppo_loss, dict(
            clip_param=cfg.clip_param, vf_coeff=cfg.vf_coeff,
            entropy_coeff=cfg.entropy_coeff,
            vf_clip_param=cfg.vf_clip_param))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        samples = self.workers.foreach(lambda a: a.sample.remote())
        if not samples:
            raise RuntimeError(
                "every env runner failed to sample this iteration "
                "(restarts exhausted?)")
        # GAE per rollout, then flatten across runners and time.
        flat: Dict[str, list] = {k: [] for k in
                                 ("obs", "actions", "logp",
                                  "advantages", "targets")}
        steps = 0
        for _, batch in samples:
            adv, targets = compute_gae(
                jnp.asarray(batch["rewards"]), jnp.asarray(batch["vf"]),
                jnp.asarray(batch["dones"]), jnp.asarray(batch["last_vf"]),
                gamma=cfg.gamma, lam=cfg.lam)
            T, B = batch["actions"].shape
            steps += T * B
            # Flatten time x batch only; feature dims (flat vectors OR
            # image HxWxC for conv encoders) pass through unchanged.
            flat["obs"].append(
                batch["obs"].reshape((T * B,) + batch["obs"].shape[2:]))
            flat["actions"].append(batch["actions"].reshape(-1))
            flat["logp"].append(batch["logp"].reshape(-1))
            flat["advantages"].append(np.asarray(adv).reshape(-1))
            flat["targets"].append(np.asarray(targets).reshape(-1))
        train_batch = {k: np.concatenate(v) for k, v in flat.items()}
        adv = train_batch["advantages"]
        train_batch["advantages"] = ((adv - adv.mean())
                                     / (adv.std() + 1e-8))
        self._timesteps_total += steps
        mb = min(cfg.minibatch_size, len(adv))
        stats = self.learner.update_minibatches(
            train_batch, minibatch_size=mb, num_epochs=cfg.num_epochs,
            seed=cfg.seed)
        self._broadcast_weights()
        result = {f"learner/{k}": v for k, v in stats.items()}
        result["num_env_steps_sampled_this_iter"] = steps
        self._merge_runner_metrics(result)
        return result
