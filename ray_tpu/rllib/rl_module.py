"""RLModule: the framework-agnostic policy container, in Flax.

Reference: rllib/core/rl_module/rl_module.py — a module exposes
forward_inference / forward_exploration / forward_train over batches.
Here modules are Flax linen modules returning {"logits", "vf"} and the
three forwards are pure jit-compiled functions of (params, obs) — the
TPU-idiomatic shape: one traced forward reused everywhere, no
stochastic Python in the hot path (sampling uses jax PRNG keys).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ray_tpu.rllib.env import Space


class ActorCriticMLP(nn.Module):
    """Default module (reference: rllib default MLP catalog encoders +
    policy/value heads)."""

    num_actions: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h)(x))
        logits = nn.Dense(self.num_actions)(x)
        vf = nn.Dense(1)(x)
        return {"logits": logits, "vf": jnp.squeeze(vf, -1)}


class ActorCriticConv(nn.Module):
    """Conv encoder for pixel observations (reference: rllib's CNN
    catalog encoders). Strided 3x3 convs feed shared dense heads; uint8
    inputs are normalized in-graph so rollouts ship raw bytes."""

    num_actions: int
    channels: Sequence[int] = (16, 32)
    hidden: int = 128

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(jnp.float32) / 255.0
        lead = x.shape[:-3]  # accept [..., H, W, C]
        x = x.reshape((-1,) + x.shape[-3:])
        for ch in self.channels:
            x = nn.relu(nn.Conv(ch, (3, 3), strides=(2, 2))(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.tanh(nn.Dense(self.hidden)(x))
        logits = nn.Dense(self.num_actions)(x)
        vf = nn.Dense(1)(x)
        return {
            "logits": logits.reshape(lead + (self.num_actions,)),
            "vf": jnp.squeeze(vf, -1).reshape(lead),
        }


@dataclasses.dataclass
class RLModuleSpec:
    """Reference: SingleAgentRLModuleSpec."""

    observation_space: Space
    action_space: Space
    model_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    module_class: Optional[type] = None

    def build(self) -> "RLModule":
        if self.module_class is not None:
            cls = self.module_class
        elif len(self.observation_space.shape) == 3:
            cls = ActorCriticConv  # pixel obs -> conv tower
        else:
            cls = ActorCriticMLP
        net = cls(num_actions=self.action_space.n,
                  **self.model_config)
        return RLModule(net, self.observation_space)


class RLModule:
    def __init__(self, net: nn.Module, obs_space: Space):
        self.net = net
        self.obs_space = obs_space

    def init_params(self, rng_key) -> Any:
        dummy = jnp.zeros((1,) + tuple(self.obs_space.shape), jnp.float32)
        return self.net.init(rng_key, dummy)

    def make_forwards(self) -> Dict[str, Callable]:
        """Build the three jit-compiled forwards."""
        net = self.net

        def forward_train(params, obs):
            return net.apply(params, obs)

        def forward_inference(params, obs):
            out = net.apply(params, obs)
            return jnp.argmax(out["logits"], axis=-1)

        def forward_exploration(params, obs, key):
            out = net.apply(params, obs)
            logits = out["logits"]
            action = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), action]
            return action, logp, out["vf"]

        return {
            "train": jax.jit(forward_train),
            "inference": jax.jit(forward_inference),
            "exploration": jax.jit(forward_exploration),
        }
