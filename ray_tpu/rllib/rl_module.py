"""RLModule: the framework-agnostic policy container, in Flax.

Reference: rllib/core/rl_module/rl_module.py — a module exposes
forward_inference / forward_exploration / forward_train over batches.
Here modules are Flax linen modules returning {"logits", "vf"} and the
three forwards are pure jit-compiled functions of (params, obs) — the
TPU-idiomatic shape: one traced forward reused everywhere, no
stochastic Python in the hot path (sampling uses jax PRNG keys).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ray_tpu.rllib.env import Space


class ActorCriticMLP(nn.Module):
    """Default module (reference: rllib default MLP catalog encoders +
    policy/value heads)."""

    num_actions: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h)(x))
        logits = nn.Dense(self.num_actions)(x)
        vf = nn.Dense(1)(x)
        return {"logits": logits, "vf": jnp.squeeze(vf, -1)}


@dataclasses.dataclass
class RLModuleSpec:
    """Reference: SingleAgentRLModuleSpec."""

    observation_space: Space
    action_space: Space
    model_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    module_class: Optional[type] = None

    def build(self) -> "RLModule":
        cls = self.module_class or ActorCriticMLP
        net = cls(num_actions=self.action_space.n,
                  **self.model_config)
        return RLModule(net, self.observation_space)


class RLModule:
    def __init__(self, net: nn.Module, obs_space: Space):
        self.net = net
        self.obs_space = obs_space

    def init_params(self, rng_key) -> Any:
        dummy = jnp.zeros((1,) + tuple(self.obs_space.shape), jnp.float32)
        return self.net.init(rng_key, dummy)

    def make_forwards(self) -> Dict[str, Callable]:
        """Build the three jit-compiled forwards."""
        net = self.net

        def forward_train(params, obs):
            return net.apply(params, obs)

        def forward_inference(params, obs):
            out = net.apply(params, obs)
            return jnp.argmax(out["logits"], axis=-1)

        def forward_exploration(params, obs, key):
            out = net.apply(params, obs)
            logits = out["logits"]
            action = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), action]
            return action, logp, out["vf"]

        return {
            "train": jax.jit(forward_train),
            "inference": jax.jit(forward_inference),
            "exploration": jax.jit(forward_exploration),
        }
