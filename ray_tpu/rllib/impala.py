"""IMPALA (reference: rllib/algorithms/impala/impala.py:667 training_step,
vtrace_torch.py).

Async actor parallelism: env runners sample continuously (their next
rollout is already in flight while the learner updates), and the
off-policy gap between the behavior policy that sampled a batch and the
current target policy is corrected with V-trace importance weighting.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.math import vtrace


def impala_loss(fwd, batch, *, gamma: float = 0.99,
                vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                clip_rho: float = 1.0, clip_c: float = 1.0):
    """V-trace actor-critic loss. Batch keeps [T, B] structure (the
    recurrence needs time ordering)."""
    T, B = batch["actions"].shape
    obs = batch["obs"].reshape((T * B,) + batch["obs"].shape[2:])
    out = fwd(obs)
    logits = out["logits"].reshape(T, B, -1)
    values = out["vf"].reshape(T, B)
    logp_all = jax.nn.log_softmax(logits)
    target_logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None], axis=-1)[..., 0]
    vs, pg_adv = vtrace(
        batch["logp"], jax.lax.stop_gradient(target_logp),
        batch["rewards"], jax.lax.stop_gradient(values),
        batch["dones"], batch["last_vf"],
        gamma=gamma, clip_rho=clip_rho, clip_c=clip_c)
    vs = jax.lax.stop_gradient(vs)
    pg_adv = jax.lax.stop_gradient(pg_adv)
    pi_loss = -jnp.mean(target_logp * pg_adv)
    vf_loss = jnp.mean((values - vs) ** 2)
    entropy = -jnp.mean(
        jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    return total, {
        "policy_loss": pi_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
    }


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho = 1.0
        self.clip_c = 1.0
        self.num_batches_per_step = 4
        self.broadcast_interval = 1  # learner updates between syncs
        self.lr = 6e-4
        self.algo_class = IMPALA

    def training(self, *, vf_coeff=None, entropy_coeff=None, clip_rho=None,
                 clip_c=None, num_batches_per_step=None,
                 broadcast_interval=None, **kwargs) -> "IMPALAConfig":
        super().training(**kwargs)
        for name, val in [("vf_coeff", vf_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("clip_rho", clip_rho), ("clip_c", clip_c),
                          ("num_batches_per_step", num_batches_per_step),
                          ("broadcast_interval", broadcast_interval)]:
            if val is not None:
                setattr(self, name, val)
        return self


class IMPALA(Algorithm):
    config_class = IMPALAConfig

    def _build(self):
        cfg = self.config
        self._build_common(impala_loss, dict(
            gamma=cfg.gamma, vf_coeff=cfg.vf_coeff,
            entropy_coeff=cfg.entropy_coeff,
            clip_rho=cfg.clip_rho, clip_c=cfg.clip_c))
        # The async pipeline: one in-flight sample per runner at all times.
        self._inflight = self.workers.call_async(
            lambda a: a.sample.remote())
        self._updates_since_broadcast = 0

    def _refill_pipeline(self):
        """Every live runner (including just-restarted ones) must always
        have exactly one sample in flight."""
        for i, actor in list(self.workers.actors.items()):
            if i not in self._inflight:
                try:
                    self._inflight[i] = actor.sample.remote()
                except Exception as e:
                    # Dead handle: route through the manager so the
                    # runner is restarted (or retired) instead of
                    # lingering forever with no in-flight work.
                    self.workers._on_failure(i, e)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        stats: Dict[str, float] = {}
        consumed = 0
        steps = 0
        while consumed < cfg.num_batches_per_step:
            if not self.workers.actors:
                raise RuntimeError(
                    "every env runner is dead (restarts exhausted)")
            self._refill_pipeline()
            ready = self.workers.fetch_ready(
                self._inflight, timeout=30.0,
                num_returns=min(len(self._inflight) or 1, 2))
            for i, batch in ready:
                T, B = batch["actions"].shape
                steps += T * B
                train_batch = {
                    "obs": jnp.asarray(batch["obs"]),
                    "actions": jnp.asarray(batch["actions"]),
                    "logp": jnp.asarray(batch["logp"]),
                    "rewards": jnp.asarray(batch["rewards"]),
                    "dones": jnp.asarray(batch["dones"]),
                    "last_vf": jnp.asarray(batch["last_vf"]),
                }
                stats = self.learner.update(train_batch)
                consumed += 1
                self._updates_since_broadcast += 1
                if (self._updates_since_broadcast
                        >= cfg.broadcast_interval):
                    self._async_broadcast_weights()
                    self._updates_since_broadcast = 0
        self._timesteps_total += steps
        result = {f"learner/{k}": v for k, v in stats.items()}
        result["num_env_steps_sampled_this_iter"] = steps
        self._merge_runner_metrics(result)
        return result

    def _async_broadcast_weights(self):
        """Fire-and-forget weight sync — samplers keep rolling with
        slightly stale weights (that's what V-trace corrects)."""
        weights_ref = ray_tpu.put(self.learner.get_weights())
        self.workers.call_async(
            lambda a: a.set_weights.remote(
                weights_ref, self.learner.weights_version))

    def cleanup(self):
        # Drain in-flight sample refs before killing runners.
        self._inflight.clear()
        super().cleanup()
