"""Advantage estimation: GAE and V-trace, as jit-compiled lax.scan.

Reference: rllib/evaluation/postprocessing.py (compute_gae_for_sample_batch)
and rllib/algorithms/impala/vtrace_torch.py. Both are time-reversed
recurrences — on TPU they compile to a single fused scan instead of a
Python loop over timesteps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnames=("gamma", "lam"))
def compute_gae(rewards, values, dones, last_values, *,
                gamma: float = 0.99, lam: float = 0.95):
    """All inputs [T, B]; last_values [B]. Returns (advantages, targets).

    delta_t = r_t + gamma * V_{t+1} * (1-done_t) - V_t
    A_t     = delta_t + gamma * lam * (1-done_t) * A_{t+1}
    """
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    nonterminal = 1.0 - dones.astype(jnp.float32)
    deltas = rewards + gamma * next_values * nonterminal - values

    def scan_fn(carry, x):
        delta, nt = x
        adv = delta + gamma * lam * nt * carry
        return adv, adv

    _, advs = jax.lax.scan(scan_fn, jnp.zeros_like(last_values),
                           (deltas, nonterminal), reverse=True)
    return advs, advs + values


@partial(jax.jit, static_argnames=("gamma", "clip_rho", "clip_c"))
def vtrace(behavior_logp, target_logp, rewards, values, dones, last_values,
           *, gamma: float = 0.99, clip_rho: float = 1.0,
           clip_c: float = 1.0):
    """V-trace targets (IMPALA, Espeholt et al. 2018). Inputs [T, B].

    rho_t = min(clip_rho, pi/mu); c_t = min(clip_c, pi/mu)
    vs_t = V_t + sum_k gamma^k (prod c) rho delta  — computed as a
    reversed scan: vs_t - V_t = delta_t + gamma c_t (vs_{t+1}-V_{t+1}).
    Returns (vs_targets [T,B], pg_advantages [T,B]).
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = jnp.minimum(clip_c, rhos)
    nonterminal = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    deltas = clipped_rhos * (
        rewards + gamma * next_values * nonterminal - values)

    def scan_fn(carry, x):
        delta, c, nt = x
        acc = delta + gamma * c * nt * carry
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(last_values),
        (deltas, cs, nonterminal), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], last_values[None]], axis=0)
    pg_adv = clipped_rhos * (
        rewards + gamma * next_vs * nonterminal - values)
    return vs, pg_adv


def explained_variance(targets, values):
    var_y = jnp.var(targets)
    return jnp.where(var_y > 0, 1 - jnp.var(targets - values) / var_y, 0.0)
