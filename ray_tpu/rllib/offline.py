"""Offline RL: episode IO, off-policy estimation, behavior cloning.

Reference: rllib/offline/ — json_writer.py / json_reader.py (sample IO),
off_policy_estimator.py + estimators/ (importance_sampling.py,
weighted_importance_sampling.py, direct_method.py, doubly_robust.py),
and the BC algorithm family (rllib/algorithms/bc). The TPU redesign:
episodes are stored whole (not row-chunked SampleBatches) because every
estimator here is a per-episode computation; all policy evaluations are
batched jit-compiled forwards over the concatenation of episodes, and
the Direct Method's Q-model is a jax FQE trained with expected-SARSA
targets under the target policy — not a torch FQE model.

Episode dict format (the unit of IO):
  obs:        [T+1, ...]   observations incl. the final one
  actions:    [T]          int32
  rewards:    [T]          float32
  logp:       [T]          float32 behavior-policy log-probs
  terminated: bool         True terminal (False = time-limit truncation)
"""

from __future__ import annotations

import base64
import glob
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


# -- array <-> json ---------------------------------------------------------


def _enc(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {"__npy__": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _dec(d: dict) -> np.ndarray:
    buf = base64.b64decode(d["__npy__"])
    return np.frombuffer(buf, dtype=d["dtype"]).reshape(d["shape"]).copy()


def _encode_episode(ep: Dict[str, Any]) -> str:
    out = {}
    for k, v in ep.items():
        out[k] = _enc(np.asarray(v)) if isinstance(
            v, (np.ndarray, list)) else v
    return json.dumps(out)


def _decode_episode(raw: dict) -> Dict[str, Any]:
    return {k: (_dec(v) if isinstance(v, dict) and "__npy__" in v else v)
            for k, v in raw.items()}


# -- writer / reader --------------------------------------------------------


class JsonWriter:
    """Write episodes as JSONL shards in a directory (reference:
    offline/json_writer.py). The first line of every shard is a header
    record carrying the spaces, so readers need no env to reconstruct a
    module."""

    def __init__(self, path: str, *, max_episodes_per_file: int = 1024,
                 num_actions: Optional[int] = None):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_per_file = max_episodes_per_file
        # Pass the true action-space cardinality when known; inference
        # from the data undercounts when the behavior policy never takes
        # the highest action id.
        self._num_actions = num_actions
        self._file = None
        self._count = 0
        self._shard = 0
        self._header: Optional[dict] = None

    def write(self, episode: Dict[str, Any]) -> None:
        seen = int(np.max(episode["actions"])) + 1
        if self._header is None:
            obs = np.asarray(episode["obs"])
            self._header = {
                "type": "header",
                "obs_shape": list(obs.shape[1:]),
                "obs_dtype": str(obs.dtype),
                "num_actions": self._num_actions or seen,
            }
        if self._num_actions is None:
            self._header["num_actions"] = max(
                self._header["num_actions"], seen)
        if self._file is None or self._count >= self.max_per_file:
            self.close()
            fname = os.path.join(self.path,
                                 f"episodes-{self._shard:05d}.jsonl")
            self._file = open(fname, "w")
            self._file.write(json.dumps(self._header) + "\n")
            self._shard += 1
            self._count = 0
        self._file.write(_encode_episode(episode) + "\n")
        self._count += 1

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._header is not None:
            # Shard headers are written before later episodes can raise
            # num_actions (an early shard whose episodes never take the
            # highest action id would undercount); meta.json carries the
            # final authoritative header.
            tmp = os.path.join(self.path, ".meta.tmp")
            with open(tmp, "w") as f:
                json.dump(self._header, f)
            os.replace(tmp, os.path.join(self.path, "meta.json"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JsonReader:
    """Read JSONL episode shards (reference: offline/json_reader.py).
    Accepts a directory, a glob, a file path, or a list of them."""

    def __init__(self, paths):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            p = str(p)
            if os.path.isdir(p):
                files.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
            elif any(ch in p for ch in "*?["):
                files.extend(sorted(glob.glob(p)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no episode files under {paths!r}")
        self.files = files
        self.header = self._read_header()

    def _read_header(self) -> dict:
        # Prefer the writer's final meta.json; shard headers can
        # undercount num_actions (written before later episodes).
        for f0 in self.files:
            meta = os.path.join(os.path.dirname(f0), "meta.json")
            if os.path.exists(meta):
                with open(meta) as f:
                    return json.load(f)
        header = None
        for path in self.files:
            with open(path) as f:
                first = json.loads(f.readline())
            if first.get("type") != "header":
                raise ValueError(f"{path} has no header line")
            if header is None:
                header = first
            else:
                header["num_actions"] = max(header["num_actions"],
                                            first["num_actions"])
        return header

    @property
    def obs_shape(self):
        return tuple(self.header["obs_shape"])

    @property
    def obs_dtype(self):
        return np.dtype(self.header["obs_dtype"])

    @property
    def num_actions(self) -> int:
        return int(self.header["num_actions"])

    def read_episodes(self) -> Iterator[Dict[str, Any]]:
        for path in self.files:
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    rec = json.loads(line)
                    if rec.get("type") == "header":
                        continue
                    yield _decode_episode(rec)

    def to_transitions(self) -> Dict[str, np.ndarray]:
        """Flatten all episodes into SARSA transitions: obs, actions,
        rewards, next_obs, dones (done only on a TRUE terminal — a
        truncation bootstraps), logp."""
        obs, acts, rews, nxt, dones, logps = [], [], [], [], [], []
        for ep in self.read_episodes():
            T = len(ep["actions"])
            obs.append(ep["obs"][:T])
            nxt.append(ep["obs"][1:T + 1])
            acts.append(ep["actions"])
            rews.append(ep["rewards"])
            logps.append(ep["logp"])
            d = np.zeros(T, np.bool_)
            if ep.get("terminated", True):
                d[-1] = True
            dones.append(d)
        return {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(acts).astype(np.int32),
            "rewards": np.concatenate(rews).astype(np.float32),
            "next_obs": np.concatenate(nxt),
            "dones": np.concatenate(dones),
            "logp": np.concatenate(logps).astype(np.float32),
        }


def collect_episodes(env_spec, module_spec, params, *,
                     num_episodes: int, num_envs: int = 8, seed: int = 0,
                     max_steps: int = 1000,
                     writer: Optional[JsonWriter] = None
                     ) -> List[Dict[str, Any]]:
    """Roll out the policy and return complete episodes (optionally
    streaming them into a writer) — the data-generation half of the
    reference's ``output`` config."""
    import jax

    from ray_tpu.rllib.env import make_vec

    env = make_vec(env_spec, num_envs, seed=seed)
    if writer is not None and writer._num_actions is None:
        writer._num_actions = env.action_space.n
    module = module_spec.build()
    forwards = module.make_forwards()
    key = jax.random.PRNGKey(seed)
    obs = env.reset(seed=seed)
    B = env.num_envs
    traj: List[Dict[str, list]] = [
        {"obs": [obs[i]], "actions": [], "rewards": [], "logp": []}
        for i in range(B)]
    episodes: List[Dict[str, Any]] = []
    steps = 0
    while len(episodes) < num_episodes and steps < max_steps:
        key, sub = jax.random.split(key)
        action, logp, _ = forwards["exploration"](params, obs, sub)
        action = np.asarray(action)
        logp = np.asarray(logp)
        next_obs, rew, term, trunc = env.step(action)
        done = term | trunc
        final = env.final_obs
        for i in range(B):
            t = traj[i]
            t["actions"].append(int(action[i]))
            t["rewards"].append(float(rew[i]))
            t["logp"].append(float(logp[i]))
            if done[i]:
                last = (final[i] if final is not None else next_obs[i])
                ep = {
                    "obs": np.stack(t["obs"] + [last]),
                    "actions": np.asarray(t["actions"], np.int32),
                    "rewards": np.asarray(t["rewards"], np.float32),
                    "logp": np.asarray(t["logp"], np.float32),
                    "terminated": bool(term[i]),
                }
                episodes.append(ep)
                traj[i] = {"obs": [next_obs[i]], "actions": [],
                           "rewards": [], "logp": []}
            else:
                t["obs"].append(next_obs[i])
        obs = next_obs
        steps += 1
    episodes = episodes[:num_episodes]
    if len(episodes) < num_episodes:
        import logging

        logging.getLogger(__name__).warning(
            "collect_episodes: hit max_steps=%d with only %d/%d "
            "episodes complete", max_steps, len(episodes), num_episodes)
    # Write exactly the returned set so the on-disk dataset and the
    # returned one agree (the last vectorized step can finish several
    # episodes past the request).
    if writer is not None:
        for ep in episodes:
            writer.write(ep)
    return episodes


# -- off-policy estimators --------------------------------------------------


class OffPolicyEstimator:
    """Estimate the value of a TARGET policy from BEHAVIOR-policy
    episodes (reference: offline/off_policy_estimator.py). Subclasses
    implement estimate_on_single_episode-equivalent math vectorized
    over the whole episode set; target-policy log-probs come from one
    batched jit forward over every step in the dataset."""

    def __init__(self, module_spec, params, *, gamma: float = 0.99):
        import jax
        import jax.numpy as jnp

        self.gamma = gamma
        self.params = params
        module = module_spec.build()
        net = module.net

        def _logp_probs(p, obs):
            out = net.apply(p, obs)
            logp = jax.nn.log_softmax(out["logits"])
            return logp, jnp.exp(logp)

        self._logp_probs = jax.jit(_logp_probs)

    def _target_logps(self, episodes) -> List[np.ndarray]:
        """Per-episode arrays of log pi_target(a_t | s_t)."""
        obs = np.concatenate([ep["obs"][:len(ep["actions"])]
                              for ep in episodes])
        acts = np.concatenate([ep["actions"] for ep in episodes])
        logp_all, _ = self._logp_probs(self.params, obs)
        logp_all = np.asarray(logp_all)
        flat = logp_all[np.arange(len(acts)), acts]
        out, lo = [], 0
        for ep in episodes:
            T = len(ep["actions"])
            out.append(flat[lo:lo + T])
            lo += T
        return out

    @staticmethod
    def _behavior_return(ep, gamma: float) -> float:
        r = np.asarray(ep["rewards"])
        return float((gamma ** np.arange(len(r))) @ r)

    def estimate(self, episodes: Sequence[Dict[str, Any]]
                 ) -> Dict[str, float]:
        v_b = float(np.mean([self._behavior_return(ep, self.gamma)
                             for ep in episodes]))
        v_t = self._estimate_target(list(episodes))
        return {
            "v_behavior": v_b,
            "v_target": v_t,
            "v_gain": v_t / v_b if v_b else float("nan"),
            "num_episodes": len(episodes),
        }

    def _estimate_target(self, episodes) -> float:
        raise NotImplementedError

    def _cum_weights(self, episodes) -> List[np.ndarray]:
        """Per-episode cumulative importance weights w_t =
        prod_{k<=t} pi_target(a_k|s_k) / pi_behavior(a_k|s_k)."""
        tlogps = self._target_logps(episodes)
        out = []
        for ep, tl in zip(episodes, tlogps):
            rho = np.exp(tl - np.asarray(ep["logp"]))
            out.append(np.cumprod(rho))
        return out


class ImportanceSampling(OffPolicyEstimator):
    """Per-decision ordinary IS (reference: estimators/
    importance_sampling.py): V = mean_ep sum_t gamma^t w_t r_t."""

    def _estimate_target(self, episodes) -> float:
        ws = self._cum_weights(episodes)
        vals = []
        for ep, w in zip(episodes, ws):
            r = np.asarray(ep["rewards"])
            g = self.gamma ** np.arange(len(r))
            vals.append(float(np.sum(g * w * r)))
        return float(np.mean(vals))


class WeightedImportanceSampling(OffPolicyEstimator):
    """Per-decision WIS (reference: estimators/
    weighted_importance_sampling.py): weights at step t are normalized
    by their mean over episodes alive at t, trading a little bias for
    much lower variance."""

    def _estimate_target(self, episodes) -> float:
        ws = self._cum_weights(episodes)
        max_t = max(len(w) for w in ws)
        # Mean cumulative weight per step over episodes that reach it.
        wbar = np.zeros(max_t)
        cnt = np.zeros(max_t)
        for w in ws:
            wbar[:len(w)] += w
            cnt[:len(w)] += 1
        wbar = wbar / np.maximum(cnt, 1)
        vals = []
        for ep, w in zip(episodes, ws):
            r = np.asarray(ep["rewards"])
            g = self.gamma ** np.arange(len(r))
            norm = np.where(wbar[:len(w)] > 0, wbar[:len(w)], 1.0)
            vals.append(float(np.sum(g * (w / norm) * r)))
        return float(np.mean(vals))


class _FQE:
    """Fitted Q Evaluation: a small jax Q-network regressed on expected-
    SARSA targets under the target policy (reference: estimators/
    fqe_torch_model.py, redesigned as a jit-compiled optax loop)."""

    def __init__(self, obs_shape, num_actions: int, *, gamma: float,
                 hidden=(64, 64), lr: float = 1e-2, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax
        from flax import linen as nn

        class QNet(nn.Module):
            n: int
            hidden: tuple

            @nn.compact
            def __call__(self, obs):
                x = obs.astype(jnp.float32)
                x = x.reshape((x.shape[0], -1))
                for h in self.hidden:
                    x = nn.relu(nn.Dense(h)(x))
                return nn.Dense(self.n)(x)

        self.net = QNet(num_actions, tuple(hidden))
        dummy = jnp.zeros((1,) + tuple(obs_shape), jnp.float32)
        self.q_params = self.net.init(jax.random.PRNGKey(seed), dummy)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.q_params)
        self.gamma = gamma
        net, tx, gamma_ = self.net, self.tx, gamma

        def step(qp, opt_state, batch):
            # Expected-SARSA target under pi_target; (1-done) cuts the
            # bootstrap at true terminals.
            q_next = net.apply(qp, batch["next_obs"])
            v_next = jnp.sum(batch["next_probs"] * q_next, axis=-1)
            target = batch["rewards"] + gamma_ * v_next * (
                1.0 - batch["dones"])
            target = jax.lax.stop_gradient(target)

            def loss_fn(p):
                q = net.apply(p, batch["obs"])
                qa = q[jnp.arange(q.shape[0]), batch["actions"]]
                return jnp.mean((qa - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(qp)
            updates, new_opt = tx.update(grads, opt_state, qp)
            return optax.apply_updates(qp, updates), new_opt, loss

        self._step = jax.jit(step)
        self._apply = jax.jit(lambda p, obs: net.apply(p, obs))

    def train(self, transitions: Dict[str, np.ndarray],
              next_probs: np.ndarray, *, iterations: int = 200,
              batch_size: int = 256, seed: int = 0) -> float:
        import jax.numpy as jnp

        n = len(transitions["actions"])
        rng = np.random.default_rng(seed)
        loss = 0.0
        for _ in range(iterations):
            idx = rng.integers(0, n, size=min(batch_size, n))
            batch = {
                "obs": jnp.asarray(transitions["obs"][idx]),
                "actions": jnp.asarray(transitions["actions"][idx]),
                "rewards": jnp.asarray(transitions["rewards"][idx]),
                "next_obs": jnp.asarray(transitions["next_obs"][idx]),
                "dones": jnp.asarray(
                    transitions["dones"][idx].astype(np.float32)),
                "next_probs": jnp.asarray(next_probs[idx]),
            }
            self.q_params, self.opt_state, loss = self._step(
                self.q_params, self.opt_state, batch)
        return float(loss)

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._apply(self.q_params, obs))


class DirectMethod(OffPolicyEstimator):
    """DM (reference: estimators/direct_method.py): fit Q^pi by FQE,
    then V = mean_ep E_{a ~ pi(s_0)} Q(s_0, a)."""

    def __init__(self, module_spec, params, *, gamma: float = 0.99,
                 fqe_iterations: int = 1000, seed: int = 0):
        super().__init__(module_spec, params, gamma=gamma)
        self.fqe_iterations = fqe_iterations
        self.seed = seed
        self._fqe: Optional[_FQE] = None

    def _fit(self, episodes) -> _FQE:
        trans = _episodes_to_transitions(episodes)
        num_actions = int(np.max(trans["actions"])) + 1
        _, next_probs = self._logp_probs(self.params, trans["next_obs"])
        num_actions = max(num_actions, np.asarray(next_probs).shape[-1])
        fqe = _FQE(trans["obs"].shape[1:], num_actions,
                   gamma=self.gamma, seed=self.seed)
        fqe.train(trans, np.asarray(next_probs),
                  iterations=self.fqe_iterations, seed=self.seed)
        return fqe

    def _estimate_target(self, episodes) -> float:
        self._fqe = self._fit(episodes)
        s0 = np.stack([ep["obs"][0] for ep in episodes])
        _, probs0 = self._logp_probs(self.params, s0)
        q0 = self._fqe.q_values(s0)
        return float(np.mean(np.sum(np.asarray(probs0) * q0, axis=-1)))


class DoublyRobust(DirectMethod):
    """DR (reference: estimators/doubly_robust.py): the Jiang & Li
    backward recursion v_t = V(s_t) + rho_t (r_t + gamma v_{t+1} -
    Q(s_t, a_t)) combining the FQE model with per-decision IS."""

    def _estimate_target(self, episodes) -> float:
        self._fqe = self._fit(episodes)
        tlogps = self._target_logps(episodes)
        # ONE batched forward over the concatenation of all episode
        # steps (per-episode forwards would recompile the jit function
        # for every distinct episode length).
        all_obs = np.concatenate([ep["obs"][:len(ep["actions"])]
                                  for ep in episodes])
        q_all = self._fqe.q_values(all_obs)
        _, probs_all = self._logp_probs(self.params, all_obs)
        probs_all = np.asarray(probs_all)
        vals, lo = [], 0
        for ep, tl in zip(episodes, tlogps):
            T = len(ep["actions"])
            q = q_all[lo:lo + T]
            v = np.sum(probs_all[lo:lo + T] * q, axis=-1)
            lo += T
            qa = q[np.arange(T), ep["actions"]]
            rho = np.exp(tl - np.asarray(ep["logp"]))
            acc = 0.0
            for t in range(T - 1, -1, -1):
                acc = v[t] + rho[t] * (
                    ep["rewards"][t] + self.gamma * acc - qa[t])
            vals.append(float(acc))
        return float(np.mean(vals))


def _episodes_to_transitions(episodes) -> Dict[str, np.ndarray]:
    obs, acts, rews, nxt, dones = [], [], [], [], []
    for ep in episodes:
        T = len(ep["actions"])
        obs.append(ep["obs"][:T])
        nxt.append(ep["obs"][1:T + 1])
        acts.append(np.asarray(ep["actions"], np.int32))
        rews.append(np.asarray(ep["rewards"], np.float32))
        d = np.zeros(T, np.bool_)
        if ep.get("terminated", True):
            d[-1] = True
        dones.append(d)
    return {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(acts),
        "rewards": np.concatenate(rews),
        "next_obs": np.concatenate(nxt),
        "dones": np.concatenate(dones),
    }


# -- behavior cloning -------------------------------------------------------


def bc_loss(fwd, batch):
    """Negative log-likelihood of the dataset actions (reference:
    rllib/algorithms/bc — BC's policy loss without its MARWIL scaffold)."""
    import jax
    import jax.numpy as jnp

    out = fwd(batch["obs"])
    logp = jax.nn.log_softmax(out["logits"])
    nll = -jnp.mean(logp[jnp.arange(logp.shape[0]), batch["actions"]])
    return nll, {"bc_loss": nll}


class BCConfig:
    """Offline behavior-cloning config (reference:
    rllib/algorithms/bc/bc.py:BCConfig)."""

    def __init__(self):
        self.input_: Any = None
        self.lr = 1e-3
        self.train_batch_size = 256
        self.model: Dict[str, Any] = {}
        self.grad_clip: Optional[float] = None
        self.seed = 0
        self.algo_class = BC

    def offline_data(self, *, input_=None) -> "BCConfig":
        if input_ is not None:
            self.input_ = input_
        return self

    def training(self, *, lr=None, train_batch_size=None, model=None,
                 grad_clip=None) -> "BCConfig":
        if lr is not None:
            self.lr = lr
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if model is not None:
            self.model = model
        if grad_clip is not None:
            self.grad_clip = grad_clip
        return self

    def debugging(self, *, seed=None) -> "BCConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "BC":
        algo = BC()
        algo.setup({"bc_config": self})
        return algo


from ray_tpu.tune.trainable import Trainable as _Trainable


class BC(_Trainable):
    """Behavior cloning from offline episodes (reference:
    rllib/algorithms/bc). Supervised -log pi(a|s) on dataset
    transitions via the standard JaxLearner; a real tune.Trainable
    (setup from a flat param dict, checkpointable), so
    ``tune.Tuner(BC, param_space={"input_": ..., "lr": ...})`` works
    like the reference's Tune integration."""

    def __init__(self):
        self.iteration = 0

    def setup(self, config):
        from ray_tpu.rllib.env import Space
        from ray_tpu.rllib.learner import JaxLearner
        from ray_tpu.rllib.rl_module import RLModuleSpec

        if isinstance(config, BCConfig):
            cfg = config
        elif isinstance(config, dict) and "bc_config" in config:
            cfg = config["bc_config"]
        else:
            # Flat Tune-style param dict.
            cfg = BCConfig()
            for k, v in (config or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
        self.config = cfg
        if cfg.input_ is None:
            raise ValueError("BCConfig.offline_data(input_=...) required")
        self.reader = JsonReader(cfg.input_)
        obs_space = Space(self.reader.obs_shape, self.reader.obs_dtype)
        act_space = Space.discrete(self.reader.num_actions)
        self.module_spec = RLModuleSpec(obs_space, act_space,
                                        model_config=dict(cfg.model))
        self.learner = JaxLearner(
            self.module_spec, bc_loss, lr=cfg.lr,
            grad_clip=cfg.grad_clip, seed=cfg.seed)
        trans = self.reader.to_transitions()
        self._obs = trans["obs"]
        self._actions = trans["actions"]
        self._rng = np.random.default_rng(cfg.seed)
        self.iteration = 0

    def training_step(self) -> Dict[str, Any]:
        n = len(self._actions)
        idx = self._rng.integers(0, n, size=min(
            self.config.train_batch_size, n))
        metrics = self.learner.update(
            {"obs": self._obs[idx], "actions": self._actions[idx]})
        metrics["num_samples_trained"] = len(idx)
        return metrics

    def step(self) -> Dict[str, Any]:
        result = self.training_step()
        self.iteration += 1
        result["training_iteration"] = self.iteration
        return result

    train = step

    def get_policy_params(self):
        return self.learner.get_weights()

    def get_state(self) -> dict:
        return {"learner": self.learner.get_state(),
                "iteration": self.iteration}

    def set_state(self, state: dict) -> None:
        self.learner.set_state(state["learner"])
        self.iteration = state["iteration"]

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        import pickle

        with open(os.path.join(checkpoint_dir, "bc_state.pkl"),
                  "wb") as f:
            pickle.dump(self.get_state(), f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import pickle

        with open(os.path.join(checkpoint_dir, "bc_state.pkl"),
                  "rb") as f:
            self.set_state(pickle.load(f))

    def stop(self):
        pass

    cleanup = stop
