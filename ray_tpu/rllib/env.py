"""RL environments: gymnasium-style API + built-in vectorized envs.

Reference: rllib/env/env_runner.py consumes gymnasium vector envs; here
the built-in envs are pure-numpy *vectorized-first* implementations
(CartPole, a discrete GridWorld) so the rollout hot loop is array math
feeding batched jax policy forwards — no per-env Python stepping, no gym
dependency. Custom envs plug in via the same VectorEnv protocol or a
single-env class auto-wrapped by ``make_vec``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Space:
    def __init__(self, shape: Tuple[int, ...], dtype, n: Optional[int] = None):
        self.shape = shape
        self.dtype = dtype
        self.n = n  # discrete cardinality (None = continuous box)

    @staticmethod
    def discrete(n: int) -> "Space":
        return Space((), np.int32, n)

    @staticmethod
    def box(shape: Tuple[int, ...]) -> "Space":
        return Space(shape, np.float32)


class VectorEnv:
    """B independent env copies stepped as one batch."""

    observation_space: Space
    action_space: Space
    num_envs: int

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """-> (obs, rewards, terminateds, truncateds). Auto-resets done
        sub-envs (the returned obs for done envs is the fresh reset).
        When any sub-env TRUNCATED this step, ``self.final_obs`` holds
        the pre-reset observation batch (rows meaningful where done) so
        runners can bootstrap V(s_final); otherwise it is None — envs
        should not pay a second render on the common path."""
        raise NotImplementedError

    final_obs: Optional[np.ndarray] = None


class CartPoleVecEnv(VectorEnv):
    """Vectorized CartPole-v1 dynamics (standard Barto-Sutton constants;
    behaviorally matches gymnasium's CartPole for RL purposes)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.observation_space = Space.box((4,))
        self.action_space = Space.discrete(2)
        self._rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float32)
        self.steps = np.zeros(num_envs, np.int64)

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4)).astype(np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.state = self._sample_state(self.num_envs)
        self.steps[:] = 0
        return self.state.copy()

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self.state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pm_len = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pm_len * theta_dot ** 2 * sintheta) / total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.POLE_HALF_LEN
            * (4.0 / 3.0 - self.POLE_MASS * costheta ** 2 / total_mass))
        x_acc = temp - pm_len * theta_acc * costheta / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self.state = np.stack([x, x_dot, theta, theta_dot],
                              axis=1).astype(np.float32)
        self.steps += 1
        terminated = ((np.abs(x) > self.X_LIMIT)
                      | (np.abs(theta) > self.THETA_LIMIT))
        truncated = self.steps >= self.MAX_STEPS
        reward = np.ones(self.num_envs, np.float32)
        done = terminated | truncated
        self.final_obs = (self.state.copy() if truncated.any()
                          else None)
        if done.any():
            n = int(done.sum())
            self.state[done] = self._sample_state(n)
            self.steps[done] = 0
        return self.state.copy(), reward, terminated, truncated


class GridWorldVecEnv(VectorEnv):
    """Tiny deterministic 1-D corridor: move right to the goal. Used for
    fast learning tests (reference analog: rllib's debugging envs)."""

    def __init__(self, num_envs: int = 8, length: int = 5, seed: int = 0):
        self.num_envs = num_envs
        self.length = length
        self.observation_space = Space.box((length,))
        self.action_space = Space.discrete(2)
        self.pos = np.zeros(num_envs, np.int64)
        self.steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        obs = np.zeros((self.num_envs, self.length), np.float32)
        obs[np.arange(self.num_envs), self.pos] = 1.0
        return obs

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        self.pos[:] = 0
        self.steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        self.pos = np.clip(self.pos + np.where(actions == 1, 1, -1),
                           0, self.length - 1)
        self.steps += 1
        terminated = self.pos == self.length - 1
        truncated = self.steps >= 3 * self.length
        reward = np.where(terminated, 1.0, -0.01).astype(np.float32)
        done = terminated | truncated
        self.final_obs = self._obs() if truncated.any() else None
        if done.any():
            self.pos[done] = 0
            self.steps[done] = 0
        return self._obs(), reward, terminated, truncated


class PixelGridWorldVecEnv(VectorEnv):
    """Pixel-observation GridWorld: obs is a (size, size, 3) uint8 image
    (agent = red pixel, goal = green), rendered for the whole batch with
    fancy indexing — the vectorized pixel env that makes image-pipeline
    throughput numbers meaningful (reference analog: rllib's
    Atari/pixel envs feeding conv towers)."""

    def __init__(self, num_envs: int = 8, size: int = 16, seed: int = 0):
        self.num_envs = num_envs
        self.size = size
        self.observation_space = Space((size, size, 3), np.uint8)
        self.action_space = Space.discrete(4)  # up/down/left/right
        self._rng = np.random.default_rng(seed)
        self.pos = np.zeros((num_envs, 2), np.int64)
        self.goal = np.full((num_envs, 2), size - 1, np.int64)
        self.steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        n, s = self.num_envs, self.size
        obs = np.zeros((n, s, s, 3), np.uint8)
        idx = np.arange(n)
        obs[idx, self.goal[:, 0], self.goal[:, 1], 1] = 255
        obs[idx, self.pos[:, 0], self.pos[:, 1], 0] = 255
        return obs

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        self.pos[:] = 0
        self.steps[:] = 0
        return self._obs()

    _MOVES = np.array([[-1, 0], [1, 0], [0, -1], [0, 1]], np.int64)

    def step(self, actions: np.ndarray):
        self.pos = np.clip(self.pos + self._MOVES[actions], 0,
                           self.size - 1)
        self.steps += 1
        terminated = (self.pos == self.goal).all(axis=1)
        truncated = self.steps >= 8 * self.size
        reward = np.where(terminated, 1.0, -0.01).astype(np.float32)
        done = terminated | truncated
        # final_obs (pre-reset observation, for time-limit bootstraps)
        # is rendered only when a truncation actually happened — the
        # common-path step renders ONCE, not twice.
        self.final_obs = self._obs() if truncated.any() else None
        if done.any():
            self.pos[done] = 0
            self.steps[done] = 0
        return self._obs(), reward, terminated, truncated


class AtariLikeVecEnv(VectorEnv):
    """Atari-class observation pipeline: 84x84x4 uint8 frame stacks
    (~28 KiB/obs — the exact volume of preprocessed Atari, ~37x the
    16x16x3 gridworld) with vectorized pong-like dynamics. Synthetic on
    purpose: BASELINE.md's north star is pipeline THROUGHPUT per chip
    ("PPO Atari >= 50k env-steps/s/chip"), and the honest cost being
    measured is rendering + frame-stack rolling + conv-tower forwards
    over real Atari-sized bytes, not ALE emulation fidelity."""

    H = W = 84
    STACK = 4

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.observation_space = Space((self.H, self.W, self.STACK),
                                       np.uint8)
        self.action_space = Space.discrete(6)  # Atari-style action set
        self._rng = np.random.default_rng(seed)
        n = num_envs
        self.ball = np.zeros((n, 2), np.float32)     # (y, x)
        self.vel = np.zeros((n, 2), np.float32)
        self.paddle = np.zeros(n, np.float32)        # paddle y
        self.steps = np.zeros(n, np.int64)
        self.obs = np.zeros((n, self.H, self.W, self.STACK), np.uint8)
        self._reset_balls(np.ones(n, bool))

    def _reset_balls(self, mask):
        m = int(mask.sum())
        if not m:
            return
        self.ball[mask, 0] = self._rng.uniform(10, self.H - 10, m)
        self.ball[mask, 1] = self.W // 2
        ang = self._rng.uniform(-0.6, 0.6, m)
        sign = self._rng.choice([-1.0, 1.0], m)
        self.vel[mask, 0] = np.sin(ang) * 2.0
        self.vel[mask, 1] = np.cos(ang) * 2.0 * sign

    def _render_frame(self, idx=None):
        """New 84x84 frames drawn with fancy indexing — for all envs, or
        only the rows in ``idx`` (the done-row re-render must not pay a
        full-batch render; same rule as PixelGridWorldVecEnv)."""
        if idx is None:
            idx = np.arange(self.num_envs)
        n = len(idx)
        frame = np.zeros((n, self.H, self.W), np.uint8)
        frame[:, 0, :] = 60   # walls
        frame[:, -1, :] = 60
        rows = np.arange(n)
        by = np.clip(self.ball[idx, 0].astype(np.int64), 1, self.H - 3)
        bx = np.clip(self.ball[idx, 1].astype(np.int64), 0, self.W - 3)
        for dy in range(2):          # 2x2 ball
            for dx in range(2):
                frame[rows, by + dy, bx + dx] = 255
        py = np.clip(self.paddle[idx].astype(np.int64), 4, self.H - 12)
        for dy in range(8):          # 2-wide, 8-tall paddle at x=2
            frame[rows, py + dy, 2] = 200
            frame[rows, py + dy, 3] = 200
        return frame

    def reset(self, seed=None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.num_envs
        self.steps[:] = 0
        self.paddle[:] = self.H // 2
        self._reset_balls(np.ones(n, bool))
        frame = self._render_frame()
        self.obs[:] = frame[..., None]  # fill the whole stack
        return self.obs.copy()

    def step(self, actions: np.ndarray):
        n = self.num_envs
        # Paddle: actions 2/4 up, 3/5 down (Atari UP/DOWN + FIRE dirs).
        up = (actions == 2) | (actions == 4)
        down = (actions == 3) | (actions == 5)
        self.paddle += np.where(up, -3.0, 0.0) + np.where(down, 3.0, 0.0)
        self.paddle = np.clip(self.paddle, 4, self.H - 12)
        # Ball physics: bounce off top/bottom and the right wall.
        self.ball += self.vel
        hit_tb = (self.ball[:, 0] <= 1) | (self.ball[:, 0] >= self.H - 3)
        self.vel[hit_tb, 0] *= -1
        hit_r = self.ball[:, 1] >= self.W - 3
        self.vel[hit_r, 1] *= -1
        # Left edge: point scored or lost depending on paddle overlap.
        at_left = self.ball[:, 1] <= 4
        aligned = (np.abs(self.ball[:, 0] - (self.paddle + 4)) <= 5)
        returned = at_left & aligned
        missed = at_left & ~aligned
        self.vel[returned, 1] *= -1
        reward = (returned.astype(np.float32)
                  - missed.astype(np.float32))
        self.steps += 1
        terminated = missed
        truncated = self.steps >= 1000
        done = terminated | truncated
        # Roll the frame stack and render the new frame IN PLACE (the
        # memmove + render over real Atari-sized buffers is the honest
        # per-step pipeline cost).
        self.obs[..., :-1] = self.obs[..., 1:]
        self.obs[..., -1] = self._render_frame()
        self.final_obs = self.obs.copy() if truncated.any() else None
        if done.any():
            # Full auto-reset (VectorEnv contract: done rows return the
            # FRESH episode's obs): new ball + centered paddle, and the
            # whole 4-frame stack refilled — a rolled stack would leak
            # the ended episode's motion cues into the new one.
            self.steps[done] = 0
            self.paddle[done] = self.H // 2
            self._reset_balls(done)
            fresh = self._render_frame(np.flatnonzero(done))
            self.obs[done] = fresh[..., None]
        # Copy out: every env in the registry has value semantics (the
        # internal buffer mutates in place next step).
        return self.obs.copy(), reward, terminated, truncated


_ENV_REGISTRY: Dict[str, Callable[..., VectorEnv]] = {
    "CartPole-v1": CartPoleVecEnv,
    "GridWorld-v0": GridWorldVecEnv,
    "PixelGridWorld-v0": PixelGridWorldVecEnv,
    "AtariLike-v0": AtariLikeVecEnv,
}


def register_env(name: str, creator: Callable[..., VectorEnv]) -> None:
    """Reference: ray.tune.registry.register_env."""
    _ENV_REGISTRY[name] = creator


def make_vec(env: Any, num_envs: int, seed: int = 0) -> VectorEnv:
    if isinstance(env, str):
        if env not in _ENV_REGISTRY:
            raise ValueError(f"unknown env {env!r}; register_env it first")
        return _ENV_REGISTRY[env](num_envs=num_envs, seed=seed)
    if callable(env):
        return env(num_envs=num_envs, seed=seed)
    raise TypeError(f"bad env spec: {env!r}")
