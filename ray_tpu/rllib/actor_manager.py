"""FaultTolerantActorManager: async RPC fan-out with failure handling.

Reference: rllib/utils/actor_manager.py:193 — issue calls to a set of
worker actors, harvest results asynchronously, mark failed actors and
restart them. Used by PPO/IMPALA for env-runner sets.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class FaultTolerantActorManager:
    def __init__(self, make_actor: Callable[[int], Any], num_actors: int,
                 *, max_restarts: int = 3):
        self._make_actor = make_actor
        self.actors: Dict[int, Any] = {
            i: make_actor(i) for i in range(num_actors)}
        self._restarts: Dict[int, int] = {i: 0 for i in range(num_actors)}
        self.max_restarts = max_restarts

    @property
    def num_actors(self) -> int:
        return len(self.actors)

    def foreach(self, fn: Callable[[Any], Any], *, timeout: float = 120.0,
                ignore_failures: bool = True) -> List[Tuple[int, Any]]:
        """fn(actor) -> ObjectRef; gather results, restarting failures.
        Returns [(actor_index, result)] for the successful actors."""
        refs = {}
        for i, actor in list(self.actors.items()):
            try:
                refs[i] = fn(actor)
            except Exception as e:
                if not ignore_failures:
                    raise
                self._on_failure(i, e)
        out = []
        for i, ref in refs.items():
            try:
                out.append((i, ray_tpu.get(ref, timeout=timeout)))
            except Exception as e:
                if not ignore_failures:
                    raise
                self._on_failure(i, e)
        return out

    def call_async(self, fn: Callable[[Any], Any]) -> Dict[int, Any]:
        """Submit without waiting; returns {actor_index: ref}."""
        refs = {}
        for i, actor in list(self.actors.items()):
            try:
                refs[i] = fn(actor)
            except Exception as e:
                self._on_failure(i, e)
        return refs

    def fetch_ready(self, refs: Dict[int, Any], *, timeout: float = 0.0,
                    num_returns: int = 1) -> List[Tuple[int, Any]]:
        """Harvest completed refs from a call_async map; failed actors are
        restarted and their refs dropped."""
        if not refs:
            return []
        by_ref = {ref: i for i, ref in refs.items()}
        ready, _ = ray_tpu.wait(
            list(by_ref), num_returns=min(num_returns, len(by_ref)),
            timeout=timeout)
        out = []
        for ref in ready:
            i = by_ref[ref]
            refs.pop(i, None)
            try:
                out.append((i, ray_tpu.get(ref)))
            except Exception as e:
                self._on_failure(i, e)
        return out

    def _on_failure(self, index: int, error: Exception):
        logger.warning("actor %d failed: %s", index, error)
        actor = self.actors.pop(index, None)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        if self._restarts[index] < self.max_restarts:
            self._restarts[index] += 1
            self.actors[index] = self._make_actor(index)
        else:
            logger.error("actor %d exhausted restarts", index)

    def shutdown(self):
        for actor in self.actors.values():
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        self.actors.clear()
