"""EnvRunner: rollout-collecting actor.

Reference: rllib/env/single_agent_env_runner.py — steps a vectorized env
with the exploration forward, returning [T, B] sample batches. Episode
returns are tracked across batch boundaries for metrics.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class EnvRunner:
    def __init__(self, env_spec, num_envs: int, rollout_length: int,
                 module_spec, seed: int = 0, gamma: float = 0.99,
                 env_to_module=None, module_to_env=None):
        import jax

        from ray_tpu.rllib.connectors import build_pipeline
        from ray_tpu.rllib.env import make_vec

        self.env = make_vec(env_spec, num_envs, seed=seed)
        self._env_spec = env_spec
        self._seed = seed
        self._env_to_module_raw = env_to_module
        self._module_to_env_raw = module_to_env
        self.rollout_length = rollout_length
        self.gamma = gamma
        # Connector pipelines (reference: env_runner's env-to-module /
        # module-to-env ConnectorV2 pipelines). The module consumes and
        # trains on POST-pipeline observations; module_spec is expected
        # to already carry the transformed space (algorithm._build_common
        # applies transform_space).
        self.env_to_module = build_pipeline(env_to_module)
        self.module_to_env = build_pipeline(module_to_env)
        self.module = module_spec.build()
        self.forwards = self.module.make_forwards()
        self.params = self.module.init_params(
            jax.random.PRNGKey(seed))
        self._key = jax.random.PRNGKey(seed + 1)
        self.obs = self._process_obs(self.env.reset(seed=seed), None)
        self._ep_returns = np.zeros(num_envs, np.float32)
        self._ep_lens = np.zeros(num_envs, np.int64)
        self._completed: list = []
        self._weights_version = 0

    def _process_obs(self, obs: np.ndarray,
                     dones: Optional[np.ndarray]) -> np.ndarray:
        if self.env_to_module is None:
            return obs
        return self.env_to_module({"obs": obs, "dones": dones})["obs"]

    def set_weights(self, params, version: int = 0) -> None:
        self.params = params
        self._weights_version = version

    def get_weights_version(self) -> int:
        return self._weights_version

    def sample(self) -> Dict[str, np.ndarray]:
        """Collect one [T, B] rollout batch."""
        import jax

        T, B = self.rollout_length, self.env.num_envs
        # Keep the obs dtype: casting uint8 pixels to float32 here
        # quadruples rollout memory traffic; the module's encoder
        # normalizes once on device (rl_module.py: /255). Shape/dtype
        # come from the (possibly connector-transformed) current obs.
        obs_buf = np.empty((T, B) + tuple(self.obs.shape[1:]),
                           self.obs.dtype)
        act_buf = np.empty((T, B), np.int32)
        logp_buf = np.empty((T, B), np.float32)
        vf_buf = np.empty((T, B), np.float32)
        rew_buf = np.empty((T, B), np.float32)
        done_buf = np.empty((T, B), np.bool_)
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, vf = self.forwards["exploration"](
                self.params, self.obs, sub)
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            vf_buf[t] = np.asarray(vf)
            if self.module_to_env is not None:
                env_action = self.module_to_env(
                    {"actions": action})["actions"]
            else:
                env_action = action
            raw_obs, rew, term, trunc = self.env.step(env_action)
            done = term | trunc
            # Episode metrics use the TRUE env reward (before any
            # bootstrap augmentation below).
            self._ep_returns += rew
            self._ep_lens += 1
            # Time-limit bootstrapping: a truncation is not a true
            # terminal — fold gamma * V(s_final) into the reward so the
            # advantage recurrence (which cuts at done) stays unbiased.
            only_trunc = trunc & ~term
            if only_trunc.any() and self.env.final_obs is not None:
                fin_obs = self.env.final_obs
                if self.env_to_module is not None:
                    # preview: transform the pre-reset obs without
                    # advancing frame stacks / filter statistics (the
                    # pipeline state still reflects the step that
                    # produced final_obs here, so the stack shift is
                    # the true end-of-episode view).
                    fin_obs = self.env_to_module.preview(
                        {"obs": fin_obs, "dones": None})["obs"]
                # Full-batch forward (fixed shape -> no per-count
                # recompiles), then select the truncated rows.
                fin = self.forwards["train"](self.params, fin_obs)
                rew = rew.copy()
                rew[only_trunc] += (
                    self.gamma * np.asarray(fin["vf"])[only_trunc])
            rew_buf[t] = rew
            done_buf[t] = done
            # Advance pipeline state only after the final_obs preview.
            self.obs = self._process_obs(raw_obs, done)
            if done.any():
                for i in np.nonzero(done)[0]:
                    self._completed.append(
                        (float(self._ep_returns[i]), int(self._ep_lens[i])))
                self._ep_returns[done] = 0.0
                self._ep_lens[done] = 0
        # Bootstrap value for the final obs.
        out = self.forwards["train"](self.params, self.obs)
        last_vf = np.asarray(out["vf"])
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "vf": vf_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "last_vf": last_vf,
            "weights_version": self._weights_version,
        }

    def get_metrics(self) -> Dict[str, Any]:
        eps = self._completed
        self._completed = []
        if not eps:
            return {"episodes_this_iter": 0}
        returns = [r for r, _ in eps]
        lens = [l for _, l in eps]
        return {
            "episodes_this_iter": len(eps),
            "episode_return_mean": float(np.mean(returns)),
            "episode_return_max": float(np.max(returns)),
            "episode_return_min": float(np.min(returns)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def evaluate(self, num_episodes: int, *, max_steps: int = 10_000,
                 seed: Optional[int] = None) -> Dict[str, Any]:
        """Greedy-policy evaluation on a FRESH env (reference: the
        evaluation EnvRunner group). The training env, its episode
        metrics, and connector pipelines are untouched — evaluation
        runs on eval_copy() pipelines: isolated deep copies that keep
        learned normalization statistics (frozen) but drop transient
        frame-stack state."""
        from ray_tpu.rllib.env import make_vec

        seed = self._seed + 777 if seed is None else seed
        env = make_vec(self._env_spec, self.env.num_envs, seed=seed)
        e2m = (self.env_to_module.eval_copy()
               if self.env_to_module is not None else None)
        m2e = (self.module_to_env.eval_copy()
               if self.module_to_env is not None else None)
        obs = env.reset(seed=seed)
        if e2m is not None:
            obs = e2m({"obs": obs, "dones": None})["obs"]
        B = env.num_envs
        ep_ret = np.zeros(B, np.float32)
        ep_len = np.zeros(B, np.int64)
        done_eps: list = []
        steps = 0
        while len(done_eps) < num_episodes and steps < max_steps:
            action = np.asarray(
                self.forwards["inference"](self.params, obs))
            if m2e is not None:
                action = m2e({"actions": action})["actions"]
            raw_obs, rew, term, trunc = env.step(action)
            done = term | trunc
            ep_ret += rew
            ep_len += 1
            if done.any():
                for i in np.nonzero(done)[0]:
                    done_eps.append((float(ep_ret[i]), int(ep_len[i])))
                ep_ret[done] = 0.0
                ep_len[done] = 0
            obs = raw_obs
            if e2m is not None:
                obs = e2m({"obs": obs, "dones": done})["obs"]
            steps += 1
        done_eps = done_eps[:num_episodes]
        if not done_eps:
            return {"episodes": 0}
        rets = [r for r, _ in done_eps]
        lens = [l for _, l in done_eps]
        return {
            "episodes": len(done_eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def get_connector_state(self) -> Optional[dict]:
        """Stateful connector state (frame stacks are transient, but
        normalization statistics must survive checkpoints)."""
        if self.env_to_module is None:
            return None
        return self.env_to_module.get_state()

    def set_connector_state(self, state: Optional[dict]) -> None:
        if state is not None and self.env_to_module is not None:
            self.env_to_module.set_state(state)

    def ping(self) -> bool:
        return True
