"""JaxLearner: gradient updates for RLModules.

Reference: rllib/core/learner/learner.py:105 (compute_loss /
compute_gradients / apply_gradients / update_from_batch) and
torch_learner.py's DDP wrap. The TPU redesign: instead of wrapping the
module in DDP and all-reducing gradients, the whole update step is one
jit-compiled function laid out over a device mesh — batch sharded on the
data axis, params replicated — and XLA inserts the gradient psums over
ICI (GSPMD data parallelism).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class JaxLearner:
    def __init__(self, module_spec, loss_fn: Callable, *,
                 lr: float = 3e-4, grad_clip: Optional[float] = 0.5,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 mesh: Optional[Mesh] = None, seed: int = 0,
                 loss_config: Optional[Dict[str, Any]] = None):
        self.module = module_spec.build()
        self.params = self.module.init_params(jax.random.PRNGKey(seed))
        tx = optimizer
        if tx is None:
            chain = []
            if grad_clip:
                chain.append(optax.clip_by_global_norm(grad_clip))
            chain.append(optax.adam(lr))
            tx = optax.chain(*chain)
        self.tx = tx
        self.opt_state = tx.init(self.params)
        self.loss_fn = loss_fn
        self.loss_config = dict(loss_config or {})
        self.mesh = mesh
        self._update = self._build_update()
        self._version = 0

    def _build_update(self):
        net = self.module.net
        loss_fn = self.loss_fn
        loss_cfg = self.loss_config
        tx = self.tx

        def step(params, opt_state, batch):
            def total_loss(p):
                fwd = lambda obs: net.apply(p, obs)  # noqa: E731
                return loss_fn(fwd, batch, **loss_cfg)

            (loss, aux), grads = jax.value_and_grad(
                total_loss, has_aux=True)(params)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            return new_params, new_opt_state, aux

        if self.mesh is not None:
            # GSPMD data parallelism: params/opt replicated, batch sharded
            # on the mesh's data axis; XLA inserts the gradient psum.
            repl = NamedSharding(self.mesh, P())
            data = NamedSharding(self.mesh, P("data"))
            return jax.jit(
                step,
                in_shardings=(repl, repl, data),
                out_shardings=(repl, repl, repl),
            )
        return jax.jit(step)

    def update(self, batch: Dict[str, Any]) -> Dict[str, float]:
        """One gradient step on a flat [N, ...] batch."""
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch)
        self._version += 1
        return {k: float(v) for k, v in aux.items()}

    def update_minibatches(self, batch: Dict[str, np.ndarray], *,
                           minibatch_size: int, num_epochs: int,
                           seed: int = 0) -> Dict[str, float]:
        """SGD epochs over shuffled minibatches (reference:
        learner.py update_from_batch with minibatching)."""
        n = len(next(iter(batch.values())))
        rng = np.random.default_rng(seed + self._version)
        last: Dict[str, float] = {}
        for _ in range(num_epochs):
            perm = rng.permutation(n)
            for lo in range(0, n - minibatch_size + 1, minibatch_size):
                idx = perm[lo:lo + minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                last = self.update(mb)
        return last

    # -- weights --------------------------------------------------------
    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = params

    @property
    def weights_version(self) -> int:
        return self._version

    def get_state(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "version": self._version,
        }

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self._version = state["version"]
