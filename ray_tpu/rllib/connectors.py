"""Connector pipelines: declarative obs/action transforms between env
and module.

Reference: rllib/connectors/connector_v2.py (ConnectorV2 pieces with an
env-to-module and a module-to-env direction) and
connector_pipeline_v2.py (ordered pipeline with insert/prepend/append
surgery). The TPU-shaped difference: connectors here operate on whole
vectorized [B, ...] batches (numpy in the rollout loop, never per-env
Python), and each connector declares how it transforms the observation
space so the RLModule is built against the *post-pipeline* space.

Data flows as a dict: env-to-module pipelines see at least
``{"obs": [B, ...], "dones": [B] | None}`` and must return the same keys;
module-to-env pipelines see ``{"actions": [B, ...]}``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env import Space


class ConnectorV2:
    """One transform stage. Stateless by default; stateful connectors
    (frame stacking, running normalization) keep per-env state keyed by
    batch row and reset it where ``dones`` is set."""

    def __call__(self, data: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def preview(self, data: Dict[str, Any]) -> Dict[str, Any]:
        """Apply the transform WITHOUT mutating connector state. Used
        for out-of-band observations (e.g. bootstrapping V(s_final) on a
        truncated episode) that must not advance frame stacks or
        normalization statistics. Stateless connectors just call
        through."""
        return self(data)

    def transform_space(self, space: Space) -> Space:
        """Observation space after this connector (identity default)."""
        return space

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass

    def begin_eval(self) -> None:
        """Prepare a COPY of this connector for evaluation rollouts:
        freeze learned statistics, drop transient per-episode state.
        Called on the deep copy, never the training instance."""

    @property
    def name(self) -> str:
        return type(self).__name__


class ConnectorPipelineV2(ConnectorV2):
    """Ordered connector list with the reference's surgery API
    (reference: connector_pipeline_v2.py — prepend/append/
    insert_before/insert_after/remove by class or name)."""

    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors: List[ConnectorV2] = list(connectors or [])

    def __call__(self, data: Dict[str, Any]) -> Dict[str, Any]:
        for c in self.connectors:
            data = c(data)
        return data

    def preview(self, data: Dict[str, Any]) -> Dict[str, Any]:
        for c in self.connectors:
            data = c.preview(data)
        return data

    def transform_space(self, space: Space) -> Space:
        for c in self.connectors:
            space = c.transform_space(space)
        return space

    # -- surgery --------------------------------------------------------
    def _index_of(self, key) -> int:
        for i, c in enumerate(self.connectors):
            if (c is key or c.name == key
                    or (isinstance(key, type) and isinstance(c, key))):
                return i
        raise ValueError(f"no connector matching {key!r}")

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def insert_before(self, key, connector) -> "ConnectorPipelineV2":
        self.connectors.insert(self._index_of(key), connector)
        return self

    def insert_after(self, key, connector) -> "ConnectorPipelineV2":
        self.connectors.insert(self._index_of(key) + 1, connector)
        return self

    def remove(self, key) -> "ConnectorPipelineV2":
        del self.connectors[self._index_of(key)]
        return self

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])

    def eval_copy(self) -> "ConnectorPipelineV2":
        """An isolated pipeline for evaluation: a deep copy (so
        instance-style connectors never share state with training) that
        KEEPS learned statistics (the policy was trained on normalized
        obs — reference RLlib likewise syncs filters to eval workers)
        but freezes them and drops per-episode transients."""
        import copy

        clone = copy.deepcopy(self)
        for c in clone.connectors:
            c.begin_eval()
        return clone


# -- env-to-module connectors -----------------------------------------------


class FlattenObs(ConnectorV2):
    """[B, ...] -> [B, prod(...)] (reference: the flatten-observations
    env-to-module connector)."""

    def __call__(self, data):
        obs = data["obs"]
        data["obs"] = obs.reshape(obs.shape[0], -1)
        return data

    def transform_space(self, space: Space) -> Space:
        return Space((int(np.prod(space.shape)),), space.dtype)


class CastObs(ConnectorV2):
    def __init__(self, dtype=np.float32):
        self.dtype = np.dtype(dtype)

    def __call__(self, data):
        data["obs"] = data["obs"].astype(self.dtype, copy=False)
        return data

    def transform_space(self, space: Space) -> Space:
        return Space(space.shape, self.dtype, space.n)


class NormalizeObs(ConnectorV2):
    """Running mean/std normalization (reference: the mean-std filter
    connector). Welford-style batch updates; the statistics are part of
    connector state so checkpoints carry them."""

    def __init__(self, epsilon: float = 1e-8, clip: float = 10.0,
                 update: bool = True):
        self.epsilon = epsilon
        self.clip = clip
        self.update = update
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, data):
        obs = np.asarray(data["obs"], np.float32)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.ones(obs.shape[1:], np.float64)
        if self.update:
            b = obs.shape[0]
            b_mean = obs.mean(axis=0)
            b_var = obs.var(axis=0)
            delta = b_mean - self._mean
            tot = self._count + b
            self._mean = self._mean + delta * b / tot
            self._m2 = (self._m2 + b_var * b
                        + delta ** 2 * self._count * b / tot)
            self._count = tot
        std = np.sqrt(self._m2 / max(self._count, 1.0) + self.epsilon)
        out = (obs - self._mean) / std
        data["obs"] = np.clip(out, -self.clip, self.clip).astype(np.float32)
        return data

    def preview(self, data):
        obs = np.asarray(data["obs"], np.float32)
        if self._mean is None:
            data["obs"] = obs
            return data
        std = np.sqrt(self._m2 / max(self._count, 1.0) + self.epsilon)
        out = (obs - self._mean) / std
        data["obs"] = np.clip(out, -self.clip, self.clip).astype(np.float32)
        return data

    def transform_space(self, space: Space) -> Space:
        return Space(space.shape, np.float32, space.n)

    def get_state(self):
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state):
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]

    def begin_eval(self):
        self.update = False  # evaluate with frozen training statistics


class FrameStackObs(ConnectorV2):
    """Stack the last k observations along the trailing axis
    (reference: the frame-stacking env-to-module connector). Per-env
    stacks live in the connector; a done row re-seeds its stack with the
    fresh reset observation so episodes never see frames from the
    previous episode."""

    def __init__(self, k: int = 4):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self._stack: Optional[np.ndarray] = None  # [B, ..., C*k]

    def __call__(self, data):
        obs = data["obs"]
        dones = data.get("dones")
        if self._stack is None:
            self._stack = np.concatenate([obs] * self.k, axis=-1)
        else:
            c = obs.shape[-1]
            self._stack = np.concatenate(
                [self._stack[..., c:], obs], axis=-1)
            if dones is not None and dones.any():
                # Re-seed finished rows: their obs is already the fresh
                # reset (auto-reset envs); an episode must not see
                # frames from its predecessor.
                self._stack[dones] = np.concatenate(
                    [obs[dones]] * self.k, axis=-1)
        data["obs"] = self._stack.copy()
        return data

    def preview(self, data):
        obs = data["obs"]
        if self._stack is None:
            data["obs"] = np.concatenate([obs] * self.k, axis=-1)
        else:
            c = obs.shape[-1]
            data["obs"] = np.concatenate(
                [self._stack[..., c:], obs], axis=-1)
        return data

    def begin_eval(self):
        self._stack = None  # eval episodes must not see training frames

    def transform_space(self, space: Space) -> Space:
        shape = tuple(space.shape[:-1]) + (space.shape[-1] * self.k,)
        return Space(shape, space.dtype, space.n)


# -- module-to-env connectors -----------------------------------------------


class ClipActions(ConnectorV2):
    """Clip continuous actions to the env's bounds (reference: the
    clip-actions module-to-env connector)."""

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, data):
        data["actions"] = np.clip(data["actions"], self.low, self.high)
        return data


class UnsquashActions(ConnectorV2):
    """Map tanh-squashed [-1, 1] module outputs onto [low, high]
    (reference: the unsquash-actions connector)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, data):
        a = np.asarray(data["actions"], np.float32)
        data["actions"] = self.low + (a + 1.0) * 0.5 * (self.high - self.low)
        return data


def build_pipeline(connectors) -> Optional[ConnectorPipelineV2]:
    """Normalize a user-supplied connector list (instances or zero-arg
    factories) into a fresh pipeline; None/[] -> None."""
    if not connectors:
        return None
    built = [c() if (callable(c) and not isinstance(c, ConnectorV2))
             else c for c in connectors]
    return ConnectorPipelineV2(built)
