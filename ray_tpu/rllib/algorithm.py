"""Algorithm + AlgorithmConfig: the trainer shell.

Reference: rllib/algorithms/algorithm.py:192 (Algorithm(Trainable)) and
algorithm_config.py (builder with .environment/.training/.env_runners).
Algorithm subclasses ray_tpu.tune.Trainable, so `tune.Tuner(PPO, ...)`
works exactly like the reference's Tune integration; `training_step` is
the per-algorithm hook.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Builder (reference: AlgorithmConfig). Chain .environment(),
    .training(), .env_runners(), .resources(); then .build()."""

    algo_class: Optional[type] = None

    def __init__(self):
        self.env: Any = "CartPole-v1"
        self.num_envs_per_env_runner = 8
        self.num_env_runners = 2
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.grad_clip: Optional[float] = 0.5
        self.train_batch_size = 1024
        self.model: Dict[str, Any] = {}
        self.seed = 0
        self.num_cpus_per_env_runner = 1.0
        self.num_tpus_per_learner = 0.0
        # Connector factories/instances (rllib/connectors equivalent):
        # env-to-module transforms obs before the policy forward (and
        # the module is built against the transformed space);
        # module-to-env transforms actions before env.step.
        self.env_to_module_connectors: list = []
        self.module_to_env_connectors: list = []
        # Periodic greedy evaluation: 0 = only on explicit .evaluate().
        self.evaluation_interval: int = 0
        self.evaluation_num_episodes: int = 10
        self.extra: Dict[str, Any] = {}

    def environment(self, env=None, *, num_envs_per_env_runner=None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        return self

    def env_runners(self, *, num_env_runners=None,
                    rollout_fragment_length=None,
                    num_cpus_per_env_runner=None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_cpus_per_env_runner is not None:
            self.num_cpus_per_env_runner = num_cpus_per_env_runner
        return self

    def training(self, *, lr=None, gamma=None, grad_clip=None,
                 train_batch_size=None, model=None, **kwargs
                 ) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if grad_clip is not None:
            self.grad_clip = grad_clip
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if model is not None:
            self.model = model
        self.extra.update(kwargs)
        return self

    def connectors(self, *, env_to_module=None, module_to_env=None
                   ) -> "AlgorithmConfig":
        """Pass lists of ConnectorV2 instances or zero-arg factories
        (factories preferred: every env runner builds fresh state)."""
        if env_to_module is not None:
            self.env_to_module_connectors = list(env_to_module)
        if module_to_env is not None:
            self.module_to_env_connectors = list(module_to_env)
        return self

    def resources(self, *, num_tpus_per_learner=None) -> "AlgorithmConfig":
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def evaluation(self, *, evaluation_interval=None,
                   evaluation_num_episodes=None) -> "AlgorithmConfig":
        """Greedy-policy evaluation (reference: AlgorithmConfig
        .evaluation). With an interval, step() attaches an
        ``evaluation`` block every N training iterations."""
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_episodes is not None:
            self.evaluation_num_episodes = evaluation_num_episodes
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class")
        algo = self.algo_class()
        # The algorithm owns a snapshot: mutating this builder (or
        # building twice) must not touch a running algorithm's config.
        algo.setup({"algo_config": self.copy()})
        return algo


class Algorithm(Trainable):
    """Reference: Algorithm(Trainable); train() -> iteration results,
    save/restore via Trainable checkpoints."""

    config_class = AlgorithmConfig

    def setup(self, config):
        if isinstance(config, AlgorithmConfig):
            cfg = config
        elif isinstance(config, dict) and "algo_config" in config:
            cfg = config["algo_config"]
            if isinstance(cfg, dict):
                c = self.config_class()
                c.__dict__.update(cfg)
                cfg = c
        else:
            cfg = self.config_class()
            for k, v in (config or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
                else:
                    cfg.extra[k] = v
        self.config = cfg
        self.iteration = 0
        self._timesteps_total = 0
        self._episodes_total = 0
        self._build()

    def _build(self):
        """Create learner + env runner set. Subclass hook."""
        raise NotImplementedError

    def _build_common(self, loss_fn, loss_config: Dict[str, Any]):
        """Shared construction: probe env -> module spec -> learner ->
        env-runner set -> initial weight broadcast."""
        from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
        from ray_tpu.rllib.env import make_vec
        from ray_tpu.rllib.env_runner import EnvRunner
        from ray_tpu.rllib.learner import JaxLearner
        from ray_tpu.rllib.rl_module import RLModuleSpec

        from ray_tpu.rllib.connectors import build_pipeline

        cfg = self.config
        probe = make_vec(cfg.env, 1, seed=cfg.seed)
        obs_space = probe.observation_space
        probe_pipeline = build_pipeline(cfg.env_to_module_connectors)
        if probe_pipeline is not None:
            # The module consumes post-pipeline observations.
            obs_space = probe_pipeline.transform_space(obs_space)
        self.module_spec = RLModuleSpec(
            obs_space, probe.action_space,
            model_config=dict(cfg.model))
        self.learner = JaxLearner(
            self.module_spec, loss_fn, lr=cfg.lr,
            grad_clip=cfg.grad_clip, seed=cfg.seed,
            loss_config=loss_config)
        env_spec, n_envs, T = (cfg.env, cfg.num_envs_per_env_runner,
                               cfg.rollout_fragment_length)
        module_spec, ncpu, seed, gamma = (
            self.module_spec, cfg.num_cpus_per_env_runner, cfg.seed,
            cfg.gamma)

        e2m = list(cfg.env_to_module_connectors)
        m2e = list(cfg.module_to_env_connectors)

        def make_runner(i: int):
            return (ray_tpu.remote(EnvRunner)
                    .options(num_cpus=ncpu)
                    .remote(env_spec, n_envs, T, module_spec,
                            seed=seed + 1000 * (i + 1), gamma=gamma,
                            env_to_module=e2m, module_to_env=m2e))

        self.workers = FaultTolerantActorManager(
            make_runner, cfg.num_env_runners)
        self._broadcast_weights()

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        result["timesteps_total"] = self._timesteps_total
        result["episodes_total"] = self._episodes_total
        interval = getattr(self.config, "evaluation_interval", 0)
        if interval and self.iteration % interval == 0:
            result["evaluation"] = self.evaluate(
                self.config.evaluation_num_episodes)
        result["time_this_iter_s"] = time.time() - t0
        return result

    def evaluate(self, num_episodes: Optional[int] = None
                 ) -> Dict[str, Any]:
        """Greedy-policy rollouts on fresh evaluation envs, split across
        the env-runner set (reference: Algorithm.evaluate / the
        evaluation worker group). Current learner weights are synced
        first."""
        n = (num_episodes if num_episodes is not None
             else getattr(self.config, "evaluation_num_episodes", 10))
        if n <= 0:
            return {"episodes": 0}
        self._broadcast_weights()
        k = max(1, self.workers.num_actors)
        # Equal share per runner (ceil: totals may slightly exceed n —
        # a stateless closure survives the actor manager's retries).
        per_actor = max(1, -(-n // k))
        outs = self.workers.foreach(
            lambda a: a.evaluate.remote(per_actor))
        eps, rets, lens = 0, [], []
        for _, m in outs:
            got = m.get("episodes", 0)
            if got:
                eps += got
                rets.append((m["episode_return_mean"], got))
                lens.append((m["episode_len_mean"], got))
        if not eps:
            return {"episodes": 0}
        return {
            "episodes": eps,
            "episode_return_mean": float(
                sum(r * w for r, w in rets) / eps),
            "episode_len_mean": float(
                sum(l * w for l, w in lens) / eps),
        }

    def train(self) -> Dict[str, Any]:
        return self.step()

    # -- checkpointing ---------------------------------------------------
    def save_checkpoint(self, checkpoint_dir: str) -> None:
        state = self.get_state()
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "rb") as f:
            self.set_state(pickle.load(f))

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        checkpoint_dir = checkpoint_dir or os.path.join(
            os.path.expanduser("~/ray_tpu_results"),
            f"{type(self).__name__.lower()}_ckpt_{int(time.time())}")
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.save_checkpoint(checkpoint_dir)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        self.load_checkpoint(checkpoint_dir)

    def get_state(self) -> dict:
        state = {
            "learner": self.learner.get_state(),
            "iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "episodes_total": self._episodes_total,
        }
        if self.config.env_to_module_connectors:
            # Stateful connectors (normalization filters) live in the
            # runners; checkpoint the first healthy runner's state
            # (reference keeps per-worker filters and syncs through the
            # local worker similarly).
            for _, s in self.workers.foreach(
                    lambda a: a.get_connector_state.remote()):
                if s is not None:
                    state["connectors"] = s
                    break
        return state

    def set_state(self, state: dict) -> None:
        self.learner.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps_total"]
        self._episodes_total = state["episodes_total"]
        conn = state.get("connectors")
        if conn is not None:
            conn_ref = ray_tpu.put(conn)
            self.workers.foreach(
                lambda a: a.set_connector_state.remote(conn_ref))
        self._broadcast_weights()

    def _broadcast_weights(self):
        weights_ref = ray_tpu.put(self.learner.get_weights())
        self.workers.foreach(
            lambda a: a.set_weights.remote(
                weights_ref, self.learner.weights_version))

    def _merge_runner_metrics(self, result: Dict[str, Any]):
        metrics = self.workers.foreach(lambda a: a.get_metrics.remote())
        returns, lens, episodes = [], [], 0
        for _, m in metrics:
            episodes += m.get("episodes_this_iter", 0)
            if "episode_return_mean" in m:
                returns.append(m["episode_return_mean"])
                lens.append(m["episode_len_mean"])
        self._episodes_total += episodes
        result["episodes_this_iter"] = episodes
        if returns:
            result["episode_return_mean"] = float(np.mean(returns))
            result["episode_len_mean"] = float(np.mean(lens))

    def cleanup(self):
        self.workers.shutdown()

    def stop(self):
        self.cleanup()
