"""ray_tpu.rllib — reinforcement learning on the actor substrate.

Reference capability: rllib/ (Algorithm/AlgorithmConfig, RLModule,
Learner, EnvRunner, PPO, IMPALA, FaultTolerantActorManager). Compute is
jax/flax: jit-compiled forwards and update steps, lax.scan advantage
recurrences, GSPMD data parallelism on the learner.
"""

from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.connectors import (
    CastObs,
    ClipActions,
    ConnectorPipelineV2,
    ConnectorV2,
    FlattenObs,
    FrameStackObs,
    NormalizeObs,
    UnsquashActions,
)
from ray_tpu.rllib.env import (
    CartPoleVecEnv,
    GridWorldVecEnv,
    Space,
    VectorEnv,
    make_vec,
    register_env,
)
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, impala_loss
from ray_tpu.rllib.learner import JaxLearner
from ray_tpu.rllib.offline import (
    BC,
    BCConfig,
    DirectMethod,
    DoublyRobust,
    ImportanceSampling,
    JsonReader,
    JsonWriter,
    OffPolicyEstimator,
    WeightedImportanceSampling,
    bc_loss,
    collect_episodes,
)
from ray_tpu.rllib.math import compute_gae, vtrace
from ray_tpu.rllib.ppo import PPO, PPOConfig, ppo_loss
from ray_tpu.rllib.rl_module import ActorCriticMLP, RLModule, RLModuleSpec

__all__ = [
    "ActorCriticMLP",
    "Algorithm",
    "AlgorithmConfig",
    "BC",
    "BCConfig",
    "CartPoleVecEnv",
    "CastObs",
    "ClipActions",
    "ConnectorPipelineV2",
    "ConnectorV2",
    "DirectMethod",
    "DoublyRobust",
    "EnvRunner",
    "FlattenObs",
    "FrameStackObs",
    "ImportanceSampling",
    "JsonReader",
    "JsonWriter",
    "NormalizeObs",
    "OffPolicyEstimator",
    "UnsquashActions",
    "WeightedImportanceSampling",
    "bc_loss",
    "collect_episodes",
    "FaultTolerantActorManager",
    "GridWorldVecEnv",
    "IMPALA",
    "IMPALAConfig",
    "JaxLearner",
    "PPO",
    "PPOConfig",
    "RLModule",
    "RLModuleSpec",
    "Space",
    "VectorEnv",
    "compute_gae",
    "impala_loss",
    "make_vec",
    "ppo_loss",
    "register_env",
    "vtrace",
]
