"""ray_tpu.rllib — reinforcement learning on the actor substrate.

Reference capability: rllib/ (Algorithm/AlgorithmConfig, RLModule,
Learner, EnvRunner, PPO, IMPALA, FaultTolerantActorManager). Compute is
jax/flax: jit-compiled forwards and update steps, lax.scan advantage
recurrences, GSPMD data parallelism on the learner.
"""

from ray_tpu.rllib.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import (
    CartPoleVecEnv,
    GridWorldVecEnv,
    Space,
    VectorEnv,
    make_vec,
    register_env,
)
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, impala_loss
from ray_tpu.rllib.learner import JaxLearner
from ray_tpu.rllib.math import compute_gae, vtrace
from ray_tpu.rllib.ppo import PPO, PPOConfig, ppo_loss
from ray_tpu.rllib.rl_module import ActorCriticMLP, RLModule, RLModuleSpec

__all__ = [
    "ActorCriticMLP",
    "Algorithm",
    "AlgorithmConfig",
    "CartPoleVecEnv",
    "EnvRunner",
    "FaultTolerantActorManager",
    "GridWorldVecEnv",
    "IMPALA",
    "IMPALAConfig",
    "JaxLearner",
    "PPO",
    "PPOConfig",
    "RLModule",
    "RLModuleSpec",
    "Space",
    "VectorEnv",
    "compute_gae",
    "impala_loss",
    "make_vec",
    "ppo_loss",
    "register_env",
    "vtrace",
]
