"""Arrow-backed blocks.

Reference: python/ray/data/_internal/arrow_block.py (ArrowBlockAccessor).
A block may be a ``pyarrow.Table`` instead of a numpy-dict; the accessor
dispatch in block.py routes table blocks here. Columnar file reads
(parquet/csv/json) produce table blocks, and slicing / splitting /
concatenation / writes stay zero-copy in Arrow — rows are only
materialized at UDF and iteration boundaries (``to_batch`` converts to
the numpy-dict form the TPU ingest path consumes).
"""

from __future__ import annotations

import bisect
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data import block as block_mod


def is_arrow_block(block: Any) -> bool:
    try:
        import pyarrow as pa
    except ImportError:  # pragma: no cover
        return False
    return isinstance(block, pa.Table)


def block_to_arrow(block: Any):
    """Convert any block to a pyarrow.Table (no-op for table blocks)."""
    import pyarrow as pa

    if isinstance(block, pa.Table):
        return block
    return pa.table({
        k: (list(v) if getattr(v, "ndim", 1) > 1 else v)
        for k, v in block.items()
    })


def arrow_to_numpy_block(table) -> Dict[str, np.ndarray]:
    return {c: table[c].to_numpy(zero_copy_only=False)
            for c in table.column_names}


class ArrowBlockAccessor(block_mod.BlockAccessor):
    """BlockAccessor over a pyarrow.Table (zero-copy slice/take/concat)."""

    def __init__(self, block):
        self._table = block
        # note: self._block intentionally not set; every base method that
        # touches it is overridden below.

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> Optional[Dict[str, str]]:
        if self._table.num_columns == 0:
            return None
        return {f.name: str(f.type) for f in self._table.schema}

    def iter_rows(self) -> Iterator[Any]:
        names = self._table.column_names
        simple = names == [block_mod.ITEM_COL]
        for batch in self._table.to_batches():
            for row in batch.to_pylist():
                yield row[block_mod.ITEM_COL] if simple else row

    def slice(self, start: int, end: int):
        return self._table.slice(start, max(0, end - start))

    def take_indices(self, idx: np.ndarray):
        return self._table.take(idx)

    def to_batch(self) -> Dict[str, np.ndarray]:
        return arrow_to_numpy_block(self._table)

    def to_pandas(self):
        return self._table.to_pandas()

    def sample(self, n: int, sort_key: Optional[str]) -> np.ndarray:
        nrows = self.num_rows()
        if nrows == 0:
            return np.array([])
        key = sort_key or self._sort_column()
        idx = np.random.randint(0, nrows, size=min(n, nrows))
        return self._table[key].take(idx).to_numpy(zero_copy_only=False)

    def _sort_column(self) -> str:
        names = self._table.column_names
        if block_mod.ITEM_COL in names:
            return block_mod.ITEM_COL
        return names[0]

    # Sorting requires a full permutation anyway; hand back numpy blocks
    # so the downstream grouped/shuffle code sees its canonical form.
    def sort(self, key: Optional[str], descending: bool = False):
        return block_mod.BlockAccessor(self.to_batch()).sort(
            key or self._sort_column(), descending)

    def sort_partitions(self, boundaries: np.ndarray, key: Optional[str],
                        descending: bool) -> List[Any]:
        return block_mod.BlockAccessor(self.to_batch()).sort_partitions(
            boundaries, key or self._sort_column(), descending)


def concat_arrow(tables: List[Any]):
    import pyarrow as pa

    tables = [t for t in tables if t.num_rows > 0]
    if not tables:
        return pa.table({})
    return pa.concat_tables(tables, promote_options="default")
