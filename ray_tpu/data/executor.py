"""Streaming execution of physical plans over the task/actor substrate.

Reference: python/ray/data/_internal/execution/streaming_executor.py:55
(operator DAG driven with backpressure) and
_internal/planner/exchange/pull_based_shuffle_task_scheduler.py (two-phase
pull shuffle). Here each fused map stage streams block→block tasks with a
bounded in-flight window (backpressure); all-to-all ops are barriers
implemented as map tasks with ``num_returns=num_output_partitions`` so each
reduce task pulls exactly its partition from the object store — the
pull-based shuffle, with object transfer riding the runtime's data plane.

Map stages optionally run on a pool of stateful actors
(``compute="actors"``) — the reference's ActorPoolMapOperator — which is
the right execution mode for TPU inference UDFs: the actor pins the chip,
compiles once, and streams batches through the cached executable.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.context import DataContext
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    concat_blocks,
)
from ray_tpu.data import plan as plan_mod
from ray_tpu.data.plan import (
    InputData,
    Limit,
    MapStage,
    RandomShuffle,
    RandomizeBlockOrder,
    Read,
    Repartition,
    Sort,
    Union,
    Zip,
    apply_transforms,
    fuse_plan,
)

# A bundle is (ObjectRef[Block], BlockMetadata).
Bundle = Tuple[Any, BlockMetadata]


# ---------------------------------------------------------------------------
# remote task bodies (top-level so they pickle cleanly)
# ---------------------------------------------------------------------------


def _run_read_task(read_task):
    t0 = time.perf_counter()
    block = read_task()
    meta = BlockAccessor(block).metadata()
    meta.exec_s = time.perf_counter() - t0
    return block, meta


def _run_map_stage(transforms, block: Block):
    t0 = time.perf_counter()
    out = apply_transforms(transforms, block)
    meta = BlockAccessor(out).metadata()
    meta.exec_s = time.perf_counter() - t0
    return out, meta


def _slice_concat(ranges, *blocks):
    """Assemble one output block from [(input_idx, start, end), ...]."""
    t0 = time.perf_counter()
    parts = [BlockAccessor(blocks[i]).slice(s, e) for (i, s, e) in ranges]
    out = concat_blocks(parts)
    meta = BlockAccessor(out).metadata()
    meta.exec_s = time.perf_counter() - t0
    return out, meta


def _even_split_bytes(bundles: List[Bundle], n_out: int) -> int:
    """Byte-backpressure estimate for an all-to-all output block: the
    input total split evenly over the outputs."""
    total = sum((m.size_bytes or 0) for _, m in bundles)
    return total // max(1, n_out)


def plan_row_slice(bundles: List[Bundle], lo: int, hi: int):
    """Map a global row range [lo, hi) onto per-block sub-ranges.

    Returns (ranges, refs) for _slice_concat: ranges are
    (index-into-refs, start, end) against each overlapping block.
    """
    starts = np.cumsum([0] + [m.num_rows for _, m in bundles])
    ranges, refs = [], []
    for i, (ref, _) in enumerate(bundles):
        s, e = int(starts[i]), int(starts[i + 1])
        ov_lo, ov_hi = max(lo, s), min(hi, e)
        if ov_lo < ov_hi:
            ranges.append((len(refs), ov_lo - s, ov_hi - s))
            refs.append(ref)
    return ranges, refs


def _shuffle_map(block: Block, num_out: int, seed):
    acc = BlockAccessor(block)
    n = acc.num_rows()
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, num_out, size=n)
    parts = tuple(acc.take_indices(np.nonzero(assign == j)[0])
                  for j in range(num_out))
    return parts[0] if num_out == 1 else parts


def _shuffle_reduce(seed, *parts):
    t0 = time.perf_counter()
    out = concat_blocks(list(parts))
    acc = BlockAccessor(out)
    rng = np.random.default_rng(seed)
    out = acc.take_indices(rng.permutation(acc.num_rows()))
    meta = BlockAccessor(out).metadata()
    meta.exec_s = time.perf_counter() - t0
    return out, meta


def _push_shuffle_map(block: Block, reducers, shuffle_id: str,
                      map_idx: int, n_out: int, seed):
    """Push-shuffle map: partition the block and push each fragment
    directly to the reducer actor owning its partition (reference:
    _internal/planner/exchange/push_based_shuffle_task_scheduler.py —
    fragments flow to mergers while other maps still run, instead of
    parking n_in x n_out objects for a later pull phase). Each reducer
    owns n_out/len(reducers) partitions, so the actor count tracks the
    cluster size rather than the output block count."""
    acc = BlockAccessor(block)
    n = acc.num_rows()
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n_out, size=n)
    # ONE push per (map, reducer) carrying every owned partition: at
    # n_out=64 the per-partition accept calls (n_in x n_out RPCs) cost
    # more than the shuffle itself.
    by_reducer: dict = {}
    for j in range(n_out):
        part = acc.take_indices(np.nonzero(assign == j)[0])
        by_reducer.setdefault(j % len(reducers), {})[j] = part
    acks = [reducers[r].accept_many.remote(shuffle_id, map_idx, parts)
            for r, parts in by_reducer.items()]
    # Delivery barrier: the map only reports done once every reducer has
    # its fragments, so finish() can never race a straggler fragment.
    ray_tpu.get(acks, timeout=600)
    return n


class _ShuffleReducer:
    """Accumulates pushed fragments for the partitions it owns; emits
    one shuffled output block per partition. Fragments are namespaced by
    shuffle id so one cached reducer pool serves any number of
    (possibly concurrent) shuffles."""

    def __init__(self):
        self.parts: dict = {}  # (shuffle_id, partition) -> fragments
        # Shuffles fully finished on this reducer: straggler duplicate
        # pushes (free-retry double execution) for them are dropped, not
        # accumulated into orphaned fragment lists. Bounded history.
        self.done = collections.deque(maxlen=128)
        self.done_set: set = set()

    def ping(self) -> bool:
        return True

    def accept(self, shuffle_id: str, map_key, j: int,
               part: Block) -> int:
        """Idempotent per (shuffle, map, partition): a map task retried
        after its worker died re-pushes fragments that may already have
        landed; duplicates must not inflate the shuffle output."""
        if shuffle_id in self.done_set:
            return 0
        seen = self.parts.setdefault((shuffle_id, "seen"), set())
        if (map_key, j) in seen:
            return 0
        seen.add((map_key, j))
        # Keyed by map index, NOT arrival order: finish() concatenates
        # in sorted map order so a seeded shuffle is deterministic
        # across runs (map completion order is a race). Fragment count
        # per partition is bounded by the map count, so the per-object
        # overhead an eager merge would save is modest.
        frags = self.parts.setdefault((shuffle_id, j), {})
        frags[map_key] = part
        return len(frags)

    def accept_many(self, shuffle_id: str, map_key,
                    parts: dict) -> int:
        """Batched accept: every partition this reducer owns from one
        map task in a single call (same idempotence per partition)."""
        total = 0
        for j, part in parts.items():
            total += self.accept(shuffle_id, map_key, j, part)
        return total

    def finish(self, shuffle_id: str, j: int, seed, last: bool = False):
        """Emit partition j. `last` marks this reducer's final owned
        partition of the shuffle (actor calls run in submission order,
        so it arrives after every other finish): only then is the dedup
        set dropped — popping it on the first finish would let a
        straggler duplicate push double-count rows in partitions this
        reducer still owns."""
        frag_map = self.parts.pop((shuffle_id, j), {})
        out = concat_blocks([frag_map[k] for k in sorted(frag_map)])
        if last:
            self.parts.pop((shuffle_id, "seen"), None)
            if shuffle_id not in self.done_set:
                if len(self.done) == self.done.maxlen:
                    self.done_set.discard(self.done[0])
                self.done.append(shuffle_id)
                self.done_set.add(shuffle_id)
        acc = BlockAccessor(out)
        rng = np.random.default_rng(seed)
        out = acc.take_indices(rng.permutation(acc.num_rows()))
        return out, BlockAccessor(out).metadata()


# Session-cached reducer pool: reducer actors are reusable across
# shuffles (fragments are shuffle-id-namespaced), so only the first
# push shuffle pays actor startup (the reference similarly reuses its
# merge workers across rounds within a shuffle).
_reducer_pool: List[Any] = []


def _get_reducer_pool(n: int) -> List[Any]:
    global _reducer_pool
    alive = []
    for r in _reducer_pool:
        try:
            if ray_tpu.get(r.ping.remote(), timeout=5):
                alive.append(r)
        except Exception:
            pass
    _reducer_pool = alive
    reducer_cls = ray_tpu.remote(_ShuffleReducer)
    created = []
    while len(_reducer_pool) + len(created) < n:
        created.append(reducer_cls.options(num_cpus=0.01).remote())
    if created:
        # Barrier: reducers MUST be alive before any map is submitted.
        # Maps hold a full CPU while blocking on accept() delivery; if
        # the reducer creations queue behind them, nothing can ever
        # place the actors and the shuffle deadlocks.
        ray_tpu.get([r.ping.remote() for r in created], timeout=300)
        _reducer_pool.extend(created)
    return _reducer_pool[:n]


def _sort_sample(block: Block, n: int, key):
    return BlockAccessor(block).sample(n, key)


def _sort_map(block: Block, boundaries, key, descending):
    parts = tuple(BlockAccessor(block).sort_partitions(
        np.asarray(boundaries), key, descending))
    return parts[0] if len(parts) == 1 else parts


def _sort_reduce(key, descending, *parts):
    t0 = time.perf_counter()
    merged = concat_blocks(list(parts))
    out = BlockAccessor(merged).sort(key, descending)
    meta = BlockAccessor(out).metadata()
    meta.exec_s = time.perf_counter() - t0
    return out, meta


def _truncate(block: Block, n: int):
    t0 = time.perf_counter()
    out = BlockAccessor(block).slice(0, n)
    meta = BlockAccessor(out).metadata()
    meta.exec_s = time.perf_counter() - t0
    return out, meta


def _zip_blocks(left: Block, right: Block):
    t0 = time.perf_counter()
    left = BlockAccessor(left).to_batch()
    right = BlockAccessor(right).to_batch()
    out = dict(left)
    for k, v in right.items():
        name = k
        while name in out:
            name = name + "_1"
        out[name] = v
    meta = BlockAccessor(out).metadata()
    meta.exec_s = time.perf_counter() - t0
    return out, meta


class _MapActor:
    """Stateful map worker (reference: ActorPoolMapOperator's _MapWorker).

    Instantiates callable-class UDFs once in __init__ so model weights /
    compiled executables persist across blocks.
    """

    def __init__(self, transforms):
        self.transforms = []
        for t in transforms:
            fn = t.fn
            if isinstance(fn, type):  # callable class UDF
                fn = fn(*t.fn_args, **t.fn_kwargs)
                t = plan_mod.MapTransform(
                    kind=t.kind, fn=fn, batch_size=t.batch_size)
            self.transforms.append(t)

    def process(self, block: Block):
        t0 = time.perf_counter()
        out = apply_transforms(self.transforms, block)
        meta = BlockAccessor(out).metadata()
        meta.exec_s = time.perf_counter() - t0
        return out, meta


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


class StreamingExecutor:
    def __init__(self, terminal_op, *, max_in_flight: Optional[int] = None,
                 stats=None):
        ctx = DataContext.get_current()
        self.stages = fuse_plan(terminal_op)
        self.stats = stats  # data.stats.DatasetStats or None
        if max_in_flight is None:
            max_in_flight = ctx.max_in_flight_blocks
        if max_in_flight is None:
            try:
                cpus = int(ray_tpu.cluster_resources().get("CPU", 4))
            except Exception:
                cpus = 4
            max_in_flight = max(2, 2 * cpus)
        # Clamp: direct attribute assignment on the context singleton
        # bypasses __post_init__ validation, and a cap < 1 would make
        # _windowed admit nothing (silently empty datasets).
        self.max_in_flight = max(1, int(max_in_flight))
        self.max_in_flight_bytes = ctx.max_in_flight_bytes

    # -- public --------------------------------------------------------
    def execute(self) -> Iterator[Bundle]:
        it: Optional[Iterator[Bundle]] = None
        for stage in self.stages:
            it = self._stage_iter(stage, it)
            if self.stats is not None:
                passthrough = isinstance(
                    stage, (InputData, Limit, Union,
                            RandomizeBlockOrder))
                it = self.stats.wrap(
                    getattr(stage, "name", type(stage).__name__), it,
                    passthrough=passthrough)
        assert it is not None, "empty plan"
        return it

    def _stage_iter(self, stage, it: Optional[Iterator[Bundle]]
                    ) -> Iterator[Bundle]:
        if isinstance(stage, Read):
            return self._read_iter(stage)
        if isinstance(stage, InputData):
            return iter(stage.bundles)
        if isinstance(stage, MapStage):
            if stage.compute == "actors":
                return self._actor_map_iter(stage, it)
            return self._map_iter(stage, it)
        if isinstance(stage, Repartition):
            return self._repartition(stage, list(it))
        if isinstance(stage, RandomShuffle):
            return self._shuffle(stage, list(it))
        if isinstance(stage, RandomizeBlockOrder):
            bundles = list(it)
            order = np.random.default_rng(stage.seed).permutation(
                len(bundles))
            return iter([bundles[i] for i in order])
        if isinstance(stage, Sort):
            return self._sort(stage, list(it))
        if isinstance(stage, Limit):
            return self._limit_iter(stage, it)
        if isinstance(stage, Union):
            return self._union_iter(stage, it)
        if isinstance(stage, Zip):
            return self._zip(stage, list(it))
        raise TypeError(f"unknown stage {stage!r}")

    # -- streaming stages ----------------------------------------------
    def _windowed(self, submits: Iterator[tuple]
                  ) -> Iterator[Bundle]:
        """Drive task submissions with a bounded in-flight window,
        yielding results in submission order (deterministic output
        block order). Backpressure is block-count based, plus
        byte-based when DataContext.max_in_flight_bytes is set —
        submits may yield (block_ref, meta_ref, est_bytes) triples
        where est_bytes is the task's INPUT size (the output size is
        unknowable until it finishes); at least one task is always in
        flight so huge single blocks still make progress. Pulling from
        ``submits`` launches the task, so the byte gate has one-task
        lookahead: actual in-flight bytes can overshoot the cap by at
        most one task's input."""
        window: collections.deque = collections.deque()
        in_flight_bytes = 0
        byte_cap = self.max_in_flight_bytes
        submits = iter(submits)
        exhausted = False
        pending = None  # one prefetched submit awaiting byte budget
        while True:
            while not exhausted and len(window) < self.max_in_flight:
                if pending is None:
                    try:
                        pending = next(submits)
                    except StopIteration:
                        exhausted = True
                        break
                cost = pending[2] if len(pending) > 2 else 0
                if (byte_cap is not None and window
                        and in_flight_bytes + cost > byte_cap):
                    break  # wait for completions to free byte budget
                window.append(pending)
                in_flight_bytes += cost
                pending = None
            if not window:
                return
            entry = window.popleft()
            block_ref, meta_ref = entry[0], entry[1]
            in_flight_bytes -= entry[2] if len(entry) > 2 else 0
            meta = ray_tpu.get(meta_ref)
            yield block_ref, meta

    def _read_iter(self, stage: Read) -> Iterator[Bundle]:
        fn = ray_tpu.remote(_run_read_task).options(num_returns=2)

        def submits():
            for task in stage.read_tasks:
                yield tuple(fn.remote(task))

        return self._windowed(submits())

    def _map_iter(self, stage: MapStage, upstream: Iterator[Bundle]
                  ) -> Iterator[Bundle]:
        opts = dict(stage.ray_remote_args)
        opts["num_returns"] = 2
        fn = ray_tpu.remote(_run_map_stage).options(**opts)
        transforms = stage.transforms

        def submits():
            for block_ref, meta in upstream:
                yield (*fn.remote(transforms, block_ref),
                       meta.size_bytes or 0)

        return self._windowed(submits())

    def _actor_map_iter(self, stage: MapStage, upstream: Iterator[Bundle]
                        ) -> Iterator[Bundle]:
        n = stage.concurrency or 2
        opts = dict(stage.ray_remote_args)
        actor_cls = ray_tpu.remote(_MapActor).options(**opts)
        actors = [actor_cls.remote(stage.transforms) for _ in range(n)]
        try:
            idx = 0

            def submits():
                nonlocal idx
                for block_ref, meta in upstream:
                    a = actors[idx % len(actors)]
                    idx += 1
                    yield (*a.process.options(num_returns=2)
                           .remote(block_ref), meta.size_bytes or 0)

            yield from self._windowed(submits())
        finally:
            for a in actors:
                ray_tpu.kill(a)

    def _limit_iter(self, stage: Limit, upstream: Iterator[Bundle]
                    ) -> Iterator[Bundle]:
        remaining = stage.limit
        fn = ray_tpu.remote(_truncate).options(num_returns=2)
        for block_ref, meta in upstream:
            if remaining <= 0:
                return
            if meta.num_rows <= remaining:
                remaining -= meta.num_rows
                yield block_ref, meta
            else:
                b, m = fn.remote(block_ref, remaining)
                yield b, ray_tpu.get(m)
                remaining = 0

    def _union_iter(self, stage: Union, upstream: Iterator[Bundle]
                    ) -> Iterator[Bundle]:
        yield from upstream
        for other in stage.others:
            yield from StreamingExecutor(
                other, max_in_flight=self.max_in_flight).execute()

    # -- all-to-all stages ---------------------------------------------
    def _repartition(self, stage: Repartition, bundles: List[Bundle]
                     ) -> Iterator[Bundle]:
        if stage.shuffle:
            return self._shuffle(
                RandomShuffle(stage.input_op, seed=0), bundles,
                num_out=stage.num_blocks)
        total = sum(m.num_rows for _, m in bundles)
        n_out = max(1, stage.num_blocks)
        cuts = np.linspace(0, total, n_out + 1).astype(int)
        fn = ray_tpu.remote(_slice_concat).options(num_returns=2)
        # Byte-backpressure estimate: outputs are even row splits, so
        # each costs ~ the input total / n_out.
        est = _even_split_bytes(bundles, n_out)

        def submits():
            for j in range(n_out):
                ranges, refs = plan_row_slice(
                    bundles, int(cuts[j]), int(cuts[j + 1]))
                yield (*fn.remote(ranges, *refs), est)

        return self._windowed(submits())

    def _shuffle(self, stage: RandomShuffle, bundles: List[Bundle],
                 num_out: Optional[int] = None) -> Iterator[Bundle]:
        n_in = len(bundles)
        n_out = num_out or n_in
        if n_in == 0:
            return iter([])
        strategy = DataContext.get_current().resolved_shuffle_strategy()
        if strategy == "push" or (strategy == "auto" and n_in >= 8):
            return self._push_shuffle(stage, bundles, n_out)
        map_fn = ray_tpu.remote(_shuffle_map).options(num_returns=n_out)
        reduce_fn = ray_tpu.remote(_shuffle_reduce).options(num_returns=2)
        parts: List[List[Any]] = []
        for i, (ref, _) in enumerate(bundles):
            seed = None if stage.seed is None else stage.seed + i
            out = map_fn.remote(ref, n_out, seed)
            parts.append(out if isinstance(out, list) else [out])

        est = _even_split_bytes(bundles, n_out)

        def submits():
            for j in range(n_out):
                seed = None if stage.seed is None else stage.seed * 7919 + j
                yield (*reduce_fn.remote(
                    seed, *[parts[i][j] for i in range(n_in)]), est)

        return self._windowed(submits())

    def _push_shuffle(self, stage: RandomShuffle, bundles: List[Bundle],
                      n_out: int) -> Iterator[Bundle]:
        """Push-based shuffle: map fragments stream to reducer actors as
        each map finishes (no pull phase, no n_in x n_out parked
        objects). Scales where the pull shuffle's object count
        explodes."""
        import uuid

        try:
            cpus = int(ray_tpu.cluster_resources().get("CPU", 4))
        except Exception:
            cpus = 4
        n_reducers = max(1, min(n_out, cpus))
        reducers = _get_reducer_pool(n_reducers)
        shuffle_id = uuid.uuid4().hex[:12]
        map_fn = ray_tpu.remote(_push_shuffle_map)
        acks = []
        for i, (ref, _) in enumerate(bundles):
            seed = None if stage.seed is None else stage.seed + i
            acks.append(map_fn.remote(ref, reducers, shuffle_id,
                                      i, n_out, seed))
        ray_tpu.get(acks, timeout=1200)  # all fragments delivered

        est = _even_split_bytes(bundles, n_out)

        def submits():
            for j in range(n_out):
                seed = (None if stage.seed is None
                        else stage.seed * 7919 + j)
                yield (*reducers[j % n_reducers].finish
                       .options(num_returns=2).remote(
                           shuffle_id, j, seed,
                           j + n_reducers >= n_out), est)

        yield from self._windowed(submits())

    def _sort(self, stage: Sort, bundles: List[Bundle]) -> Iterator[Bundle]:
        if not bundles:
            return iter([])
        n_out = len(bundles)
        sample_fn = ray_tpu.remote(_sort_sample)
        samples = ray_tpu.get(
            [sample_fn.remote(ref, 20, stage.key) for ref, _ in bundles])
        allsamp = np.sort(np.concatenate([s for s in samples if len(s)]))
        if len(allsamp) == 0:
            return iter(bundles)
        q = np.linspace(0, len(allsamp) - 1, n_out + 1).astype(int)[1:-1]
        boundaries = allsamp[q]
        map_fn = ray_tpu.remote(_sort_map).options(num_returns=n_out)
        reduce_fn = ray_tpu.remote(_sort_reduce).options(num_returns=2)
        parts = []
        for ref, _ in bundles:
            out = map_fn.remote(ref, boundaries.tolist(), stage.key,
                                stage.descending)
            parts.append(out if isinstance(out, list) else [out])

        est = _even_split_bytes(bundles, n_out)

        def submits():
            # sort_partitions already emits parts high-to-low for
            # descending sorts, so reduce order is always natural.
            for j in range(n_out):
                yield (*reduce_fn.remote(
                    stage.key, stage.descending,
                    *[parts[i][j] for i in range(len(bundles))]), est)

        return self._windowed(submits())

    def _zip(self, stage: Zip, left: List[Bundle]) -> Iterator[Bundle]:
        right = list(StreamingExecutor(
            stage.other, max_in_flight=self.max_in_flight).execute())
        lrows = sum(m.num_rows for _, m in left)
        rrows = sum(m.num_rows for _, m in right)
        if lrows != rrows:
            raise ValueError(
                f"zip requires equal row counts: {lrows} vs {rrows}")
        # Realign the right side to the left side's block boundaries.
        cuts, acc = [], 0
        for _, m in left:
            cuts.append((acc, acc + m.num_rows))
            acc += m.num_rows
        fn_slice = ray_tpu.remote(_slice_concat).options(num_returns=2)
        zip_fn = ray_tpu.remote(_zip_blocks).options(num_returns=2)

        def submits():
            for (lref, lmeta), (lo, hi) in zip(left, cuts):
                ranges, refs = plan_row_slice(right, lo, hi)
                raligned, _m = fn_slice.remote(ranges, *refs)
                # Output carries both sides' columns: ~2x the left
                # block's bytes.
                yield (*zip_fn.remote(lref, raligned),
                       2 * (lmeta.size_bytes or 0))

        return self._windowed(submits())
