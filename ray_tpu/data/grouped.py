"""GroupedData: sort-based groupby + aggregations.

Reference: python/ray/data/grouped_data.py (GroupedData.aggregate,
sum/min/max/mean/count/std, map_groups). Implemented as a distributed
sort on the key followed by per-block group reduction — the same
sort-based shuffle strategy the reference uses.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, block_from_rows


def _group_slices(col: np.ndarray):
    """Yield (key, start, end) runs over a sorted key column."""
    n = len(col)
    start = 0
    while start < n:
        end = start
        while end < n and col[end] == col[start]:
            end += 1
        yield col[start], start, end
        start = end


def _agg_block(block: Block, key: str, aggs: List[tuple]) -> Block:
    """aggs: list of (name, on_column, reduce_kind)."""
    acc = BlockAccessor(block)
    sorted_block = acc.sort(key)
    col = sorted_block[key]
    rows = []
    for k, s, e in _group_slices(col):
        row: Dict[str, Any] = {key: k}
        for name, on, kind in aggs:
            seg = sorted_block[on][s:e] if on else None
            if kind == "count":
                row[name] = e - s
            elif kind == "sum":
                row[name] = np.sum(seg)
            elif kind == "min":
                row[name] = np.min(seg)
            elif kind == "max":
                row[name] = np.max(seg)
            elif kind == "mean":
                row[name] = float(np.mean(seg))
            elif kind == "std":
                row[name] = float(np.std(seg, ddof=1)) if e - s > 1 else 0.0
            else:
                raise ValueError(kind)
        rows.append(row)
    return block_from_rows(rows)


def _map_groups_block(block: Block, key: str, fn: Callable) -> Block:
    acc = BlockAccessor(block)
    sorted_block = acc.sort(key)
    col = sorted_block[key]
    sacc = BlockAccessor(sorted_block)
    outs = []
    for _k, s, e in _group_slices(col):
        group = sacc.slice(s, e)
        res = fn(group)
        from ray_tpu.data.block import block_from_batch

        outs.append(block_from_batch(res))
    from ray_tpu.data.block import concat_blocks

    return concat_blocks(outs) if outs else {}


class GroupedData:
    def __init__(self, ds, key: str):
        self._ds = ds
        self._key = key

    def _sorted_by_key(self):
        # Distributed sort partitions by key range, so all rows of one
        # group land in the same output block.
        return self._ds.sort(self._key)

    def _aggregate(self, aggs: List[tuple]):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data import plan as lp

        key = self._key
        t = lp.MapTransform(
            "batches", lambda b, _k=key, _a=aggs: _agg_block(b, _k, _a))
        return Dataset(lp.MapBatches(self._sorted_by_key()._op, t))

    def count(self):
        return self._aggregate([("count()", None, "count")])

    def sum(self, on: str):
        return self._aggregate([(f"sum({on})", on, "sum")])

    def min(self, on: str):
        return self._aggregate([(f"min({on})", on, "min")])

    def max(self, on: str):
        return self._aggregate([(f"max({on})", on, "max")])

    def mean(self, on: str):
        return self._aggregate([(f"mean({on})", on, "mean")])

    def std(self, on: str):
        return self._aggregate([(f"std({on})", on, "std")])

    def aggregate(self, *aggs: tuple):
        """Each agg is a (name, on_column, kind) tuple."""
        return self._aggregate(list(aggs))

    def map_groups(self, fn: Callable):
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data import plan as lp

        key = self._key
        t = lp.MapTransform(
            "batches", lambda b, _k=key, _f=fn: _map_groups_block(b, _k, _f))
        return Dataset(lp.MapBatches(self._sorted_by_key()._op, t))
