"""DataContext: per-process execution options for Data pipelines.

Reference: python/ray/data/context.py (DataContext.get_current() — the
execution-option singleton) and
_internal/execution/backpressure_policy/ (ConcurrencyCapBackpressure-
Policy caps in-flight tasks; the resource-budget policies cap bytes).
The streaming executor reads the context at plan start: block-count
backpressure bounds concurrent tasks per stage, byte backpressure
bounds the estimated data volume in flight (input-size proxy — the
output size of a running task is unknowable until it finishes).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

_current: Optional["DataContext"] = None


@dataclass
class DataContext:
    # Max concurrently running tasks per streaming stage. None = auto
    # (2 x cluster CPUs, the reference's effective default shape).
    max_in_flight_blocks: Optional[int] = None
    # Max estimated bytes in flight per stage (input-size proxy);
    # None = unlimited. Guards pipelines whose blocks are much larger
    # than their count suggests (e.g. wide tensors).
    max_in_flight_bytes: Optional[int] = None
    # Shuffle strategy: "auto" (push at >= 8 input blocks), "pull",
    # "push". The RAY_TPU_SHUFFLE_STRATEGY env var overrides.
    shuffle_strategy: str = "auto"

    def __post_init__(self):
        if self.shuffle_strategy not in ("auto", "pull", "push"):
            raise ValueError(
                f"shuffle_strategy must be auto|pull|push, got "
                f"{self.shuffle_strategy!r}")
        if (self.max_in_flight_blocks is not None
                and self.max_in_flight_blocks < 1):
            raise ValueError("max_in_flight_blocks must be >= 1")
        if (self.max_in_flight_bytes is not None
                and self.max_in_flight_bytes < 1):
            raise ValueError("max_in_flight_bytes must be >= 1")

    @staticmethod
    def get_current() -> "DataContext":
        global _current
        if _current is None:
            _current = DataContext()
        return _current

    def resolved_shuffle_strategy(self) -> str:
        env = os.environ.get("RAY_TPU_SHUFFLE_STRATEGY")
        if env is None:
            return self.shuffle_strategy
        if env not in ("auto", "pull", "push"):
            import logging

            logging.getLogger(__name__).warning(
                "ignoring invalid RAY_TPU_SHUFFLE_STRATEGY=%r "
                "(want auto|pull|push)", env)
            return self.shuffle_strategy
        return env
