"""ray_tpu.data — streaming distributed datasets.

Reference capability: python/ray/data (Dataset, read_api, streaming
executor). See dataset.py / executor.py for the TPU-first design notes.
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import (
    DataIterator,
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)
from ray_tpu.data.grouped import GroupedData

__all__ = [
    "Block",
    "BlockAccessor",
    "BlockMetadata",
    "DataContext",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
]
