"""Lazy logical plan + optimizer.

Reference: python/ray/data/_internal/logical/ (logical operators,
`optimizers.py`) — datasets record a chain of logical operators; an
optimizer pass fuses adjacent one-to-one (map-like) operators into a
single physical stage so one task applies the whole UDF chain per block
(the reference's OperatorFusionRule). All-to-all ops (sort / shuffle /
repartition) are stage barriers.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    block_from_batch,
    block_from_rows,
)

_op_counter = itertools.count()


class LogicalOp:
    """Base logical operator; `input_op` forms a linear chain."""

    name = "Op"

    def __init__(self, input_op: Optional["LogicalOp"]):
        self.input_op = input_op
        self.id = next(_op_counter)

    def chain(self) -> List["LogicalOp"]:
        ops: List[LogicalOp] = []
        op: Optional[LogicalOp] = self
        while op is not None:
            ops.append(op)
            op = op.input_op
        return ops[::-1]

    def __repr__(self):
        return f"{self.name}[{self.id}]"


class Read(LogicalOp):
    name = "Read"

    def __init__(self, read_tasks: List[Callable[[], List[Block]]],
                 num_rows_estimate: Optional[int] = None):
        super().__init__(None)
        self.read_tasks = read_tasks
        self.num_rows_estimate = num_rows_estimate


class InputData(LogicalOp):
    """Pre-materialized blocks (from_items / from_numpy / materialize)."""

    name = "InputData"

    def __init__(self, bundles: List[Tuple[Any, Any]]):
        super().__init__(None)
        self.bundles = bundles  # list of (ObjectRef[Block], BlockMetadata)


@dataclass
class MapTransform:
    """One fused step: a block-level callable, applied in a worker task."""

    kind: str  # "batches" | "rows" | "filter" | "flat_map"
    fn: Callable
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    batch_size: Optional[int] = None

    def apply(self, block: Block) -> Block:
        acc = BlockAccessor(block)
        if self.kind == "batches":
            if self.batch_size is None or acc.num_rows() <= self.batch_size:
                return block_from_batch(
                    self.fn(acc.to_batch(), *self.fn_args, **self.fn_kwargs))
            outs = []
            for start in range(0, acc.num_rows(), self.batch_size):
                piece = BlockAccessor(
                    acc.slice(start, start + self.batch_size)).to_batch()
                outs.append(block_from_batch(
                    self.fn(piece, *self.fn_args, **self.fn_kwargs)))
            from ray_tpu.data.block import concat_blocks

            return concat_blocks(outs)
        if self.kind == "rows":
            return block_from_rows(
                [self.fn(r, *self.fn_args, **self.fn_kwargs)
                 for r in acc.iter_rows()])
        if self.kind == "filter":
            rows = [r for r in acc.iter_rows()
                    if self.fn(r, *self.fn_args, **self.fn_kwargs)]
            return block_from_rows(rows) if rows else acc.slice(0, 0)
        if self.kind == "flat_map":
            out: List[Any] = []
            for r in acc.iter_rows():
                out.extend(self.fn(r, *self.fn_args, **self.fn_kwargs))
            return block_from_rows(out)
        raise ValueError(f"unknown transform kind {self.kind}")


class AbstractMap(LogicalOp):
    """One-to-one block transform; fusable."""

    def __init__(self, input_op: LogicalOp, transform: MapTransform,
                 *, compute: Optional[str] = None,
                 ray_remote_args: Optional[dict] = None,
                 concurrency: Optional[int] = None):
        super().__init__(input_op)
        self.transform = transform
        self.compute = compute
        self.ray_remote_args = ray_remote_args or {}
        self.concurrency = concurrency


class MapBatches(AbstractMap):
    name = "MapBatches"


class MapRows(AbstractMap):
    name = "Map"


class Filter(AbstractMap):
    name = "Filter"


class FlatMap(AbstractMap):
    name = "FlatMap"


class AbstractAllToAll(LogicalOp):
    """Stage barrier: consumes all input bundles, emits new ones."""


class Repartition(AbstractAllToAll):
    name = "Repartition"

    def __init__(self, input_op: LogicalOp, num_blocks: int,
                 shuffle: bool = False):
        super().__init__(input_op)
        self.num_blocks = num_blocks
        self.shuffle = shuffle


class RandomShuffle(AbstractAllToAll):
    name = "RandomShuffle"

    def __init__(self, input_op: LogicalOp, seed: Optional[int] = None):
        super().__init__(input_op)
        self.seed = seed


class RandomizeBlockOrder(AbstractAllToAll):
    """Permute bundle order without touching block contents (cheap shuffle
    for block-granular randomness; reference: logical op of same name)."""

    name = "RandomizeBlockOrder"

    def __init__(self, input_op: LogicalOp, seed: Optional[int] = None):
        super().__init__(input_op)
        self.seed = seed


class Sort(AbstractAllToAll):
    name = "Sort"

    def __init__(self, input_op: LogicalOp, key: Optional[str],
                 descending: bool = False):
        super().__init__(input_op)
        self.key = key
        self.descending = descending


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, input_op: LogicalOp, limit: int):
        super().__init__(input_op)
        self.limit = limit


class Union(LogicalOp):
    name = "Union"

    def __init__(self, input_op: LogicalOp, others: List[LogicalOp]):
        super().__init__(input_op)
        self.others = others


class Zip(LogicalOp):
    name = "Zip"

    def __init__(self, input_op: LogicalOp, other: LogicalOp):
        super().__init__(input_op)
        self.other = other


# ---------------------------------------------------------------------------
# physical plan
# ---------------------------------------------------------------------------


@dataclass
class MapStage:
    """A fused chain of map transforms executed as one task per block."""

    transforms: List[MapTransform]
    ray_remote_args: dict
    compute: Optional[str] = None
    concurrency: Optional[int] = None
    name: str = "Map"


def fuse_plan(terminal: LogicalOp) -> List[Any]:
    """Lower the logical chain into physical stages with map fusion.

    Returns a list whose entries are either the source op (Read/InputData),
    a MapStage, or a barrier/structural logical op passed through.
    """

    stages: List[Any] = []
    pending: Optional[MapStage] = None
    for op in terminal.chain():
        if isinstance(op, AbstractMap):
            compatible = (
                pending is not None
                and pending.ray_remote_args == op.ray_remote_args
                and pending.compute == op.compute
                and pending.concurrency == op.concurrency
            )
            if compatible:
                pending.transforms.append(op.transform)
                pending.name += f"->{op.name}"
            else:
                if pending is not None:
                    stages.append(pending)
                pending = MapStage(
                    transforms=[op.transform],
                    ray_remote_args=dict(op.ray_remote_args),
                    compute=op.compute,
                    concurrency=op.concurrency,
                    name=op.name,
                )
        else:
            if pending is not None:
                stages.append(pending)
                pending = None
            stages.append(op)
    if pending is not None:
        stages.append(pending)
    return stages


def apply_transforms(transforms: List[MapTransform], block: Block) -> Block:
    for t in transforms:
        block = t.apply(block)
    return block
