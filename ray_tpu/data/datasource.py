"""Datasources: read task generation + file writers.

Reference: python/ray/data/read_api.py (read_parquet:549, read_csv:1114,
read_json:981) and datasource plugins under python/ray/data/datasource/.
A datasource turns into a list of **read tasks** — picklable zero-arg
callables, each producing one block — so reads execute as distributed
tasks and stream through the executor like any other stage.
"""

from __future__ import annotations

import functools
import glob
import os
from typing import Any, Callable, List, Optional

import numpy as np

from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    ITEM_COL,
    block_from_rows,
)

ReadTask = Callable[[], Block]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in glob.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


# -- range ------------------------------------------------------------------

def _range_block(start: int, end: int) -> Block:
    return {ITEM_COL: np.arange(start, end)}


def _range_tensor_block(start: int, end: int, shape) -> Block:
    n = end - start
    base = np.arange(start, end).reshape((n,) + (1,) * len(shape))
    return {"data": np.broadcast_to(
        base, (n,) + tuple(shape)).copy()}


def range_tasks(n: int, parallelism: int) -> List[ReadTask]:
    parallelism = max(1, min(parallelism, n or 1))
    cuts = np.linspace(0, n, parallelism + 1).astype(int)
    return [functools.partial(_range_block, int(cuts[i]), int(cuts[i + 1]))
            for i in range(parallelism)]


def range_tensor_tasks(n: int, shape, parallelism: int) -> List[ReadTask]:
    parallelism = max(1, min(parallelism, n or 1))
    cuts = np.linspace(0, n, parallelism + 1).astype(int)
    return [functools.partial(_range_tensor_block, int(cuts[i]),
                              int(cuts[i + 1]), tuple(shape))
            for i in range(parallelism)]


# -- file formats -----------------------------------------------------------

def _read_parquet_file(path: str, columns) -> Block:
    # Table blocks stay Arrow end-to-end (zero-copy slice/concat/write);
    # rows materialize only at UDF / iteration boundaries
    # (reference: _internal/arrow_block.py).
    import pyarrow.parquet as pq

    return pq.read_table(path, columns=columns)


def _read_csv_file(path: str) -> Block:
    import pyarrow.csv as pcsv

    return pcsv.read_csv(path)


def _read_json_file(path: str) -> Block:
    import pyarrow.json as pjson

    return pjson.read_json(path)


def _read_text_file(path: str) -> Block:
    with open(path, "r") as f:
        lines = [ln.rstrip("\n") for ln in f]
    return {"text": np.asarray(lines, dtype=np.str_)}


def _read_numpy_file(path: str) -> Block:
    return {"data": np.load(path)}


def _read_binary_file(path: str, include_paths: bool) -> Block:
    with open(path, "rb") as f:
        data = f.read()
    block: Block = {"bytes": np.asarray([data], dtype=object)}
    if include_paths:
        block["path"] = np.asarray([path], dtype=np.str_)
    return block


_FILE_READERS = {
    "parquet": _read_parquet_file,
    "csv": _read_csv_file,
    "json": _read_json_file,
    "text": _read_text_file,
    "numpy": _read_numpy_file,
}


def file_tasks(fmt: str, paths, **reader_kwargs) -> List[ReadTask]:
    files = _expand_paths(paths)
    if fmt == "binary":
        include_paths = reader_kwargs.get("include_paths", False)
        return [functools.partial(_read_binary_file, f, include_paths)
                for f in files]
    reader = _FILE_READERS[fmt]
    if fmt == "parquet":
        columns = reader_kwargs.get("columns")
        return [functools.partial(reader, f, columns) for f in files]
    return [functools.partial(reader, f) for f in files]


# -- in-memory sources ------------------------------------------------------

def items_tasks(items: List[Any], parallelism: int) -> List[ReadTask]:
    parallelism = max(1, min(parallelism, len(items) or 1))
    cuts = np.linspace(0, len(items), parallelism + 1).astype(int)

    def make(lo, hi):
        chunk = items[lo:hi]
        return functools.partial(block_from_rows, chunk)

    return [make(int(cuts[i]), int(cuts[i + 1])) for i in range(parallelism)]


def numpy_tasks(arrays, column: str) -> List[ReadTask]:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]

    def make(a):
        return lambda: {column: a}

    return [make(np.asarray(a)) for a in arrays]


# -- writers ----------------------------------------------------------------

def write_block(fmt: str, block: Block, path: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    acc = BlockAccessor(block)
    fname = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        from ray_tpu.data.arrow_block import block_to_arrow

        pq.write_table(block_to_arrow(block), fname)
    elif fmt == "csv":
        acc.to_pandas().to_csv(fname, index=False)
    elif fmt == "json":
        acc.to_pandas().to_json(fname, orient="records", lines=True)
    elif fmt == "numpy":
        if len(block) != 1:
            raise ValueError("write_numpy requires a single-column dataset")
        np.save(fname.replace(".numpy", ".npy"), next(iter(block.values())))
        fname = fname.replace(".numpy", ".npy")
    else:
        raise ValueError(f"unknown write format {fmt}")
    return fname
