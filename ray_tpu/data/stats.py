"""Per-operator execution statistics for Data pipelines.

Reference: python/ray/data/_internal/stats.py (DatasetStats /
StatsActor: per-operator wall time, block exec times, rows/bytes,
formatted summary). Redesigned for the pull-based streaming executor:
each stage's output iterator is wrapped with a timer that attributes
driver-blocking wall time to the stage itself (child-stage time is
subtracted via a charge stack, since stages pull from each other), and
remote task bodies stamp their execution seconds into BlockMetadata so
per-block compute time needs no extra RPCs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class StageStats:
    """One executed stage's aggregate metrics."""

    name: str
    num_blocks: int = 0
    num_rows: int = 0
    size_bytes: int = 0
    # Wall seconds the driver spent blocked in THIS stage (child-stage
    # pull time excluded).
    driver_wall_s: float = 0.0
    # Remote execution seconds, summed over this stage's blocks.
    task_exec_s: float = 0.0
    block_exec_min_s: float = float("inf")
    block_exec_max_s: float = 0.0
    # Passthrough stages (Limit/Union/RandomizeBlockOrder/InputData)
    # forward upstream blocks whose exec_s belongs to the PRODUCING
    # stage; counting it again would double-book remote compute.
    passthrough: bool = False

    def record(self, meta) -> None:
        self.num_blocks += 1
        self.num_rows += getattr(meta, "num_rows", 0) or 0
        self.size_bytes += getattr(meta, "size_bytes", 0) or 0
        exec_s = getattr(meta, "exec_s", 0.0) or 0.0
        if exec_s and not self.passthrough:
            self.task_exec_s += exec_s
            self.block_exec_min_s = min(self.block_exec_min_s, exec_s)
            self.block_exec_max_s = max(self.block_exec_max_s, exec_s)

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "num_blocks": self.num_blocks,
            "num_rows": self.num_rows,
            "size_bytes": self.size_bytes,
            "driver_wall_s": round(self.driver_wall_s, 6),
            "task_exec_s": round(self.task_exec_s, 6),
        }
        if self.num_blocks and self.task_exec_s:
            d["block_exec_min_s"] = round(self.block_exec_min_s, 6)
            d["block_exec_max_s"] = round(self.block_exec_max_s, 6)
            d["block_exec_mean_s"] = round(
                self.task_exec_s / self.num_blocks, 6)
        return d


class DatasetStats:
    """Collects StageStats across one plan execution (reference:
    DatasetStats). Pass to StreamingExecutor; read ``.stages`` /
    ``.summary_string()`` after the iterator is consumed."""

    def __init__(self):
        self.stages: List[StageStats] = []
        self.total_wall_s: float = 0.0
        # Charge stack: wrap() frames push 0.0, children add their whole
        # next() duration to the parent's top-of-stack entry so the
        # parent can subtract it from its own elapsed time.
        self._stack: List[float] = []
        self._t_start: Optional[float] = None

    def wrap(self, name: str, it: Iterator,
             passthrough: bool = False) -> Iterator:
        ss = StageStats(name, passthrough=passthrough)
        self.stages.append(ss)

        def timed() -> Iterator:
            if self._t_start is None:
                self._t_start = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                self._stack.append(0.0)
                try:
                    bundle = next(it)
                except StopIteration:
                    child = self._stack.pop()
                    dt = time.perf_counter() - t0
                    ss.driver_wall_s += dt - child
                    if self._stack:
                        self._stack[-1] += dt
                    self.total_wall_s = (time.perf_counter()
                                         - self._t_start)
                    return
                child = self._stack.pop()
                dt = time.perf_counter() - t0
                ss.driver_wall_s += dt - child
                if self._stack:
                    self._stack[-1] += dt
                ss.record(bundle[1])
                self.total_wall_s = time.perf_counter() - self._t_start
                yield bundle

        return timed()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "total_wall_s": round(self.total_wall_s, 6),
            "stages": [s.to_dict() for s in self.stages],
        }

    def summary_string(self) -> str:
        """Human-readable per-operator summary (reference: the
        Dataset.stats() text block)."""
        lines = []
        for s in self.stages:
            lines.append(
                f"Operator {s.name}: {s.num_blocks} blocks, "
                f"{s.num_rows} rows, {_fmt_bytes(s.size_bytes)}")
            lines.append(
                f"    driver wall: {s.driver_wall_s:.3f}s, remote exec "
                f"total: {s.task_exec_s:.3f}s")
            if s.num_blocks and s.task_exec_s:
                lines.append(
                    f"    block exec min/mean/max: "
                    f"{s.block_exec_min_s * 1e3:.1f}/"
                    f"{s.task_exec_s / s.num_blocks * 1e3:.1f}/"
                    f"{s.block_exec_max_s * 1e3:.1f} ms")
        lines.append(f"Total wall: {self.total_wall_s:.3f}s")
        return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.1f} {unit}" if unit != "B"
                    else f"{n} {unit}")
        n /= 1024
    return f"{n} B"
