"""Blocks: the unit of distributed data.

Reference: python/ray/data/block.py and _internal/arrow_block.py /
pandas_block.py. The reference uses Arrow tables as the interchange
format; here the canonical block is a **columnar dict of numpy arrays**,
which is the TPU-native choice: batches feed `jax.device_put` /
`jax.make_array_from_process_local_data` zero-copy, dtypes stay stable
under XLA, and there is no row-object overhead on the hot ingest path.
Row-oriented data (lists of dicts / scalars) is normalized into a single
``"item"`` column or per-key columns at block creation.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

# A Block is Dict[str, np.ndarray]; all columns share length.
Block = Dict[str, np.ndarray]

ITEM_COL = "item"


@dataclass
class BlockMetadata:
    """Sidecar stats kept in the plan without fetching block payloads.

    Reference: python/ray/data/block.py BlockMetadata (num_rows,
    size_bytes, schema, input_files).
    """

    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, str]] = None
    input_files: List[str] = field(default_factory=list)
    # Remote execution seconds that produced this block (stamped by the
    # executor's task bodies; consumed by data/stats.py).
    exec_s: float = 0.0


def _to_column(values: List[Any]) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype == object and values and isinstance(values[0], str):
        arr = np.asarray(values, dtype=np.str_)
    return arr


def block_from_rows(rows: List[Any]) -> Block:
    """Build a columnar block from python rows (dicts or scalars)."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        cols: Dict[str, List[Any]] = {}
        for r in rows:
            for k, v in r.items():
                cols.setdefault(k, []).append(v)
        n = len(rows)
        for k, v in cols.items():
            if len(v) != n:
                raise ValueError(f"ragged column {k!r}: {len(v)} != {n}")
        return {k: _to_column(v) for k, v in cols.items()}
    return {ITEM_COL: _to_column(rows)}


def block_from_batch(batch: Any) -> Block:
    """Normalize a user map_batches return value into a Block."""
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return batch  # arrow table IS a block
    except ImportError:  # pragma: no cover
        pass
    if isinstance(batch, dict):
        out = {k: np.asarray(v) for k, v in batch.items()}
        lens = {k: len(v) for k, v in out.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"ragged batch columns: {lens}")
        return out
    if isinstance(batch, np.ndarray):
        return {ITEM_COL: batch}
    if isinstance(batch, list):
        return block_from_rows(batch)
    try:  # pandas DataFrame
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return {c: batch[c].to_numpy() for c in batch.columns}
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(
        "map_batches must return dict[str, ndarray], ndarray, list, or "
        f"DataFrame; got {type(batch)}"
    )


class BlockAccessor:
    """Uniform view over a block (reference: block.py BlockAccessor).

    Dispatches on block kind: numpy-dict blocks use this class directly;
    ``pyarrow.Table`` blocks get an ArrowBlockAccessor
    (data/arrow_block.py), mirroring the reference's per-format accessor
    registry."""

    def __new__(cls, block):
        if cls is BlockAccessor and type(block) is not dict:
            from ray_tpu.data.arrow_block import (
                ArrowBlockAccessor,
                is_arrow_block,
            )

            if is_arrow_block(block):
                return super().__new__(ArrowBlockAccessor)
        return super().__new__(cls)

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        if not self._block:
            return 0
        return len(next(iter(self._block.values())))

    def size_bytes(self) -> int:
        return sum(int(a.nbytes) for a in self._block.values())

    def schema(self) -> Optional[Dict[str, str]]:
        if not self._block:
            return None
        return {k: str(v.dtype) for k, v in self._block.items()}

    def metadata(self, input_files: Optional[List[str]] = None
                 ) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files or [],
        )

    # -- row access ----------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        cols = self._block
        if not cols:
            return
        keys = list(cols)
        n = self.num_rows()
        simple = keys == [ITEM_COL]
        for i in range(n):
            if simple:
                yield cols[ITEM_COL][i].item() if cols[ITEM_COL].ndim == 1 \
                    else cols[ITEM_COL][i]
            else:
                yield {k: cols[k][i] for k in keys}

    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._block.items()}

    def take_indices(self, idx: np.ndarray) -> Block:
        return {k: v[idx] for k, v in self._block.items()}

    def to_batch(self) -> Block:
        return self._block

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(
            {k: list(v) if v.ndim > 1 else v for k, v in self._block.items()}
        )

    def sample(self, n: int, sort_key: Optional[str]) -> np.ndarray:
        nrows = self.num_rows()
        if nrows == 0:
            return np.array([])
        key = sort_key or self._sort_column()
        idx = np.random.randint(0, nrows, size=min(n, nrows))
        return self._block[key][idx]

    def _sort_column(self) -> str:
        if ITEM_COL in self._block:
            return ITEM_COL
        return next(iter(self._block))

    def sort(self, key: Optional[str], descending: bool = False) -> Block:
        col = self._block[key or self._sort_column()]
        idx = np.argsort(col, kind="stable")
        if descending:
            idx = idx[::-1]
        return self.take_indices(idx)

    def sort_partitions(self, boundaries: np.ndarray, key: Optional[str],
                        descending: bool) -> List[Block]:
        """Sort locally then split at boundary values (for range shuffle)."""
        key = key or self._sort_column()
        sorted_block = self.sort(key, descending=False)
        col = sorted_block[key]
        cuts = [0]
        for b in boundaries:
            cuts.append(int(bisect.bisect_left(col.tolist(), b)))
        cuts.append(len(col))
        acc = BlockAccessor(sorted_block)
        parts = [acc.slice(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1)]
        if descending:
            parts = [BlockAccessor(p).sort(key, True) for p in parts[::-1]]
        return parts


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
    if not blocks:
        return {}
    if any(type(b) is not dict for b in blocks):
        from ray_tpu.data.arrow_block import concat_arrow, is_arrow_block

        if all(is_arrow_block(b) for b in blocks):
            return concat_arrow(blocks)  # zero-copy chunked concat
        # Mixed kinds: normalize to numpy-dict.
        blocks = [BlockAccessor(b).to_batch() for b in blocks]
    keys = list(blocks[0])
    for b in blocks[1:]:
        if list(b) != keys:
            raise ValueError(
                f"cannot concat blocks with schemas {keys} vs {list(b)}"
            )
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


BatchUDF = Callable[[Block], Any]
RowUDF = Callable[[Any], Any]
