"""Dataset: lazy, distributed, streaming data API.

Reference: python/ray/data/dataset.py (map:246, map_batches:376,
iter_batches:3599, sort, random_shuffle, repartition, split, groupby,
write_*). Datasets are immutable handles on a logical plan; execution is
streaming and distributed over the task substrate. TPU-first details:
blocks are columnar numpy, `iter_batches(batch_format="jax")` device-puts
batches (optionally with a NamedSharding so multi-chip input pipelines
produce globally-sharded arrays), and `split()` produces per-worker
shards for trainer ingest.
"""

from __future__ import annotations

import builtins
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import datasource
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    ITEM_COL,
    concat_blocks,
)
from ray_tpu.data import plan as lp
from ray_tpu.data.executor import Bundle, StreamingExecutor


def _default_parallelism() -> int:
    try:
        return max(2, int(ray_tpu.cluster_resources().get("CPU", 4)))
    except Exception:
        return 4


class Dataset:
    def __init__(self, terminal_op: lp.LogicalOp):
        self._op = terminal_op

    # -- transforms (lazy) ---------------------------------------------
    def map(self, fn, *, fn_args=(), fn_kwargs=None, **ray_remote_args
            ) -> "Dataset":
        t = lp.MapTransform("rows", fn, fn_args, fn_kwargs or {})
        return Dataset(lp.MapRows(self._op, t,
                                  ray_remote_args=ray_remote_args))

    def map_batches(self, fn, *, batch_size: Optional[int] = None,
                    compute: Optional[str] = None,
                    concurrency: Optional[int] = None,
                    fn_args=(), fn_kwargs=None,
                    fn_constructor_args=(), fn_constructor_kwargs=None,
                    **ray_remote_args) -> "Dataset":
        if isinstance(fn, type):
            compute = compute or "actors"
            t = lp.MapTransform("batches", fn, fn_constructor_args,
                                fn_constructor_kwargs or {}, batch_size)
        else:
            t = lp.MapTransform("batches", fn, fn_args, fn_kwargs or {},
                                batch_size)
        return Dataset(lp.MapBatches(
            self._op, t, compute=compute, concurrency=concurrency,
            ray_remote_args=ray_remote_args))

    def filter(self, fn, **ray_remote_args) -> "Dataset":
        t = lp.MapTransform("filter", fn)
        return Dataset(lp.Filter(self._op, t,
                                 ray_remote_args=ray_remote_args))

    def flat_map(self, fn, **ray_remote_args) -> "Dataset":
        t = lp.MapTransform("flat_map", fn)
        return Dataset(lp.FlatMap(self._op, t,
                                  ray_remote_args=ray_remote_args))

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch, _name=name, _fn=fn):
            out = dict(batch)
            out[_name] = np.asarray(_fn(batch))
            return out

        return self.map_batches(add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch, _cols=tuple(cols)):
            return {k: v for k, v in batch.items() if k not in _cols}

        return self.map_batches(drop)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch, _cols=tuple(cols)):
            return {k: batch[k] for k in _cols}

        return self.map_batches(select)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(batch, _m=dict(mapping)):
            return {_m.get(k, k): v for k, v in batch.items()}

        return self.map_batches(rename)

    def repartition(self, num_blocks: int, *, shuffle: bool = False
                    ) -> "Dataset":
        return Dataset(lp.Repartition(self._op, num_blocks, shuffle))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(lp.RandomShuffle(self._op, seed))

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        return Dataset(lp.RandomizeBlockOrder(self._op, seed))

    def sort(self, key: Optional[str] = None, descending: bool = False
             ) -> "Dataset":
        return Dataset(lp.Sort(self._op, key, descending))

    def limit(self, n: int) -> "Dataset":
        return Dataset(lp.Limit(self._op, n))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(lp.Union(self._op, [o._op for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(lp.Zip(self._op, other._op))

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.grouped import GroupedData

        return GroupedData(self, key)

    # -- execution ------------------------------------------------------
    def _execute(self) -> Iterator[Bundle]:
        return StreamingExecutor(self._op).execute()

    def materialize(self) -> "Dataset":
        """Execute the plan, pinning result blocks in the object store."""
        return Dataset(lp.InputData(list(self._execute())))

    def stats(self) -> Dict[str, Any]:
        """Execute the plan with per-operator instrumentation
        (reference: Dataset.stats / _internal/stats.py). Returns the
        dataset totals plus a per-stage breakdown (rows, bytes, driver
        wall seconds, remote exec seconds per block) and a formatted
        ``summary`` string."""
        from ray_tpu.data.stats import DatasetStats

        collector = DatasetStats()
        bundles = list(StreamingExecutor(
            self._op, stats=collector).execute())
        out: Dict[str, Any] = {
            "num_blocks": len(bundles),
            "num_rows": sum(m.num_rows for _, m in bundles),
            "size_bytes": sum(m.size_bytes for _, m in bundles),
        }
        out.update(collector.to_dict())
        out["summary"] = collector.summary_string()
        return out

    # -- consumption ----------------------------------------------------
    def iter_internal_ref_bundles(self) -> Iterator[Bundle]:
        return self._execute()

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for block_ref, _meta in self.limit(n)._execute():
            block = ray_tpu.get(block_ref)
            out.extend(BlockAccessor(block).iter_rows())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for block_ref, _ in self._execute():
            out.extend(BlockAccessor(ray_tpu.get(block_ref)).iter_rows())
        return out

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._execute())

    def schema(self) -> Optional[Dict[str, str]]:
        for _, m in self._execute():
            if m.schema:
                return m.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s) if s else []

    def iter_rows(self) -> Iterator[Any]:
        for block_ref, _ in self._execute():
            yield from BlockAccessor(ray_tpu.get(block_ref)).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     device: Any = None,
                     sharding: Any = None) -> Iterator[Any]:
        """Stream batches. ``batch_format``: "numpy" (dict of arrays),
        "pandas", or "jax" (device-put, optionally with a NamedSharding —
        the TPU input pipeline path)."""
        carry: Optional[Block] = None
        shuffle_buf: Optional[Block] = None
        rng = np.random.default_rng(local_shuffle_seed)

        def emit(block: Block):
            return _format_batch(block, batch_format, device, sharding)

        for block_ref, _ in self._execute():
            block = ray_tpu.get(block_ref)
            if BlockAccessor(block).num_rows() == 0:
                continue
            if local_shuffle_buffer_size:
                shuffle_buf = block if shuffle_buf is None else \
                    concat_blocks([shuffle_buf, block])
                acc = BlockAccessor(shuffle_buf)
                while acc.num_rows() >= local_shuffle_buffer_size:
                    idx = rng.permutation(acc.num_rows())
                    shuffle_buf = acc.take_indices(idx)
                    acc = BlockAccessor(shuffle_buf)
                    take = min(batch_size or acc.num_rows(), acc.num_rows())
                    yield emit(acc.slice(0, take))
                    shuffle_buf = acc.slice(take, acc.num_rows())
                    acc = BlockAccessor(shuffle_buf)
                continue
            carry = block if carry is None else concat_blocks([carry, block])
            if batch_size is None:
                yield emit(carry)
                carry = None
                continue
            acc = BlockAccessor(carry)
            while acc.num_rows() >= batch_size:
                yield emit(acc.slice(0, batch_size))
                carry = acc.slice(batch_size, acc.num_rows())
                acc = BlockAccessor(carry)
        leftover = shuffle_buf if local_shuffle_buffer_size else carry
        if leftover is not None and BlockAccessor(leftover).num_rows() > 0:
            if local_shuffle_buffer_size:
                # Shuffle then drain the residual buffer in batch_size
                # chunks — the batch_size contract holds even when the
                # buffer never filled; drop_last discards at most the
                # final partial batch, not the whole residue.
                acc = BlockAccessor(leftover)
                leftover = acc.take_indices(rng.permutation(acc.num_rows()))
                acc = BlockAccessor(leftover)
                step = batch_size or acc.num_rows()
                for start in builtins.range(0, acc.num_rows(), step):
                    piece = acc.slice(start, start + step)
                    if (drop_last and batch_size
                            and BlockAccessor(piece).num_rows() < batch_size):
                        break
                    yield emit(piece)
            elif not (drop_last and batch_size):
                yield emit(leftover)

    def iter_jax_batches(self, **kwargs) -> Iterator[Any]:
        kwargs.setdefault("batch_format", "jax")
        return self.iter_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        kwargs.setdefault("batch_format", "torch")
        return self.iter_batches(**kwargs)

    # -- splits ---------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        bundles = list(self._execute())
        if equal:
            total = sum(m.num_rows for _, m in bundles)
            per = total // n
            ds = Dataset(lp.InputData(bundles))
            return [ds._slice_rows(i * per, (i + 1) * per)
                    for i in builtins.range(n)]
        chunks: List[List[Bundle]] = [[] for _ in builtins.range(n)]
        for i, b in enumerate(bundles):
            chunks[i % n].append(b)
        return [Dataset(lp.InputData(c)) for c in chunks]

    def _slice_rows(self, lo: int, hi: int) -> "Dataset":
        assert isinstance(self._op, lp.InputData)
        bundles = self._op.bundles
        from ray_tpu.data.executor import _slice_concat, plan_row_slice

        fn = ray_tpu.remote(_slice_concat).options(num_returns=2)
        ranges, refs = plan_row_slice(bundles, lo, hi)
        block_ref, meta_ref = fn.remote(ranges, *refs)
        return Dataset(lp.InputData([(block_ref, ray_tpu.get(meta_ref))]))

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        bundles = list(ds._execute())
        total = sum(m.num_rows for _, m in bundles)
        n_test = int(total * test_size) if test_size < 1 else int(test_size)
        mat = Dataset(lp.InputData(bundles))
        return (mat._slice_rows(0, total - n_test),
                mat._slice_rows(total - n_test, total))

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        return [DataIterator(s) for s in self.split(n, equal=equal)]

    # -- aggregates -----------------------------------------------------
    @staticmethod
    def _fetch_batch(block_ref) -> Block:
        """Fetch a block and normalize to the numpy-dict form (Arrow
        table blocks materialize their columns here)."""
        block = ray_tpu.get(block_ref)
        if type(block) is not dict:
            block = BlockAccessor(block).to_batch()
        return block

    @staticmethod
    def _agg_target(on: Optional[str], block: Block) -> str:
        if on is not None:
            return on
        if ITEM_COL in block:
            return ITEM_COL
        if len(block) == 1:
            return next(iter(block))
        raise ValueError(
            f"dataset has multiple columns {sorted(block)}; pass "
            f"on=<column> to aggregate")

    def _agg_column(self, col: Optional[str], red, finalize=None):
        vals = []
        for block_ref, _ in self._execute():
            block = self._fetch_batch(block_ref)
            if not block:
                continue
            col_used = self._agg_target(col, block)
            if len(block[col_used]):
                vals.append(red(block[col_used]))
        if not vals:
            return None
        out = red(np.asarray(vals))
        return finalize(out) if finalize else out

    def sum(self, on: Optional[str] = None):
        per_block = []
        for block_ref, _ in self._execute():
            block = self._fetch_batch(block_ref)
            if block:
                c = self._agg_target(on, block)
                if len(block[c]):
                    per_block.append(np.sum(block[c], axis=0))
        if not per_block:
            return None
        total = np.sum(per_block, axis=0)
        return total.item() if np.ndim(total) == 0 else total

    def min(self, on: Optional[str] = None):
        return self._agg_column(on, np.min)

    def max(self, on: Optional[str] = None):
        return self._agg_column(on, np.max)

    def mean(self, on: Optional[str] = None):
        total, count = 0.0, 0
        for block_ref, _ in self._execute():
            block = self._fetch_batch(block_ref)
            if block:
                c = self._agg_target(on, block)
                total += float(np.sum(block[c]))
                count += len(block[c])
        return total / count if count else None

    def std(self, on: Optional[str] = None):
        rows = self.take_all()
        if not rows:
            return None
        if isinstance(rows[0], dict):
            c = on or next(iter(rows[0]))
            vals = np.asarray([r[c] for r in rows])
        else:
            vals = np.asarray(rows)
        return float(np.std(vals, ddof=1))

    def unique(self, column: str) -> List[Any]:
        out = set()
        for block_ref, _ in self._execute():
            block = self._fetch_batch(block_ref)
            if block and column in block:
                out.update(np.unique(block[column]).tolist())
        return sorted(out)

    # -- output ---------------------------------------------------------
    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor(ray_tpu.get(r)).to_pandas()
                  for r, _ in self._execute()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def to_numpy_refs(self) -> List[Any]:
        return [r for r, _ in self._execute()]

    def _write(self, fmt: str, path: str, **kwargs) -> List[str]:
        fn = ray_tpu.remote(datasource.write_block)
        refs = [fn.remote(fmt, block_ref, path, i)
                for i, (block_ref, _) in enumerate(self._execute())]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str) -> List[str]:
        return self._write("parquet", path)

    def write_csv(self, path: str) -> List[str]:
        return self._write("csv", path)

    def write_json(self, path: str) -> List[str]:
        return self._write("json", path)

    def write_numpy(self, path: str) -> List[str]:
        return self._write("numpy", path)

    def num_blocks(self) -> int:
        return len(list(self._execute()))

    def __repr__(self):
        return f"Dataset(plan={'->'.join(o.name for o in self._op.chain())})"


def _format_batch(block: Block, batch_format: str, device, sharding):
    if batch_format == "pyarrow":
        from ray_tpu.data.arrow_block import block_to_arrow

        return block_to_arrow(block)
    if type(block) is not dict:
        # Arrow table block: materialize columns for the numpy-family
        # formats (pandas goes through the accessor natively).
        if batch_format == "pandas":
            return BlockAccessor(block).to_pandas()
        block = BlockAccessor(block).to_batch()
    if batch_format == "numpy":
        if list(block) == [ITEM_COL]:
            return block[ITEM_COL]
        return block
    if batch_format == "pandas":
        return BlockAccessor(block).to_pandas()
    if batch_format == "jax":
        import jax

        def put(a):
            if a.dtype == object or a.dtype.kind in "US":
                return a
            if sharding is not None:
                return jax.device_put(a, sharding)
            if device is not None:
                return jax.device_put(a, device)
            return jax.device_put(a)

        if list(block) == [ITEM_COL]:
            return put(block[ITEM_COL])
        return {k: put(v) for k, v in block.items()}
    if batch_format == "torch":
        import torch

        def tt(a):
            if a.dtype == object or a.dtype.kind in "US":
                return a
            return torch.as_tensor(a)

        if list(block) == [ITEM_COL]:
            return tt(block[ITEM_COL])
        return {k: tt(v) for k, v in block.items()}
    raise ValueError(f"unknown batch_format {batch_format!r}")


class DataIterator:
    """Per-worker shard iterator (reference: ray.data.DataIterator as
    returned by streaming_split, used for Train ingest)."""

    def __init__(self, ds: Dataset):
        self._ds = ds

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return self._ds.iter_batches(**kwargs)

    def iter_rows(self) -> Iterator[Any]:
        return self._ds.iter_rows()

    def materialize(self) -> Dataset:
        return self._ds.materialize()

    def count(self) -> int:
        return self._ds.count()


# ---------------------------------------------------------------------------
# creation API (reference: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    if parallelism <= 0:
        parallelism = min(_default_parallelism(), max(1, n // 50 or 1))
    return Dataset(lp.Read(datasource.range_tasks(n, parallelism),
                           num_rows_estimate=n))


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = min(_default_parallelism(), max(1, n // 50 or 1))
    return Dataset(lp.Read(
        datasource.range_tensor_tasks(n, shape, parallelism),
        num_rows_estimate=n))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = min(_default_parallelism(),
                          max(1, len(items) // 50 or 1))
    return Dataset(lp.Read(datasource.items_tasks(list(items), parallelism)))


def from_numpy(arrays, *, column: str = "data") -> Dataset:
    return Dataset(lp.Read(datasource.numpy_tasks(arrays, column)))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]

    def make(df):
        cols = {c: df[c].to_numpy() for c in df.columns}
        return lambda: cols

    return Dataset(lp.Read([make(df) for df in dfs]))


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]

    def make(t):
        cols = {c: t[c].to_numpy(zero_copy_only=False)
                for c in t.column_names}
        return lambda: cols

    return Dataset(lp.Read([make(t) for t in tables]))


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    return Dataset(lp.Read(
        datasource.file_tasks("parquet", paths, columns=columns)))


def read_csv(paths) -> Dataset:
    return Dataset(lp.Read(datasource.file_tasks("csv", paths)))


def read_json(paths) -> Dataset:
    return Dataset(lp.Read(datasource.file_tasks("json", paths)))


def read_text(paths) -> Dataset:
    return Dataset(lp.Read(datasource.file_tasks("text", paths)))


def read_numpy(paths) -> Dataset:
    return Dataset(lp.Read(datasource.file_tasks("numpy", paths)))


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    return Dataset(lp.Read(datasource.file_tasks(
        "binary", paths, include_paths=include_paths)))
