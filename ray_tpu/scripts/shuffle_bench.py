"""Shuffle benchmark: push-based vs pull-based random_shuffle.

Reference comparison point: the push-based shuffle scheduler
(_internal/planner/exchange/push_based_shuffle_task_scheduler.py) exists
because the pull shuffle's n_in x n_out object fan-out stops scaling.
Run: python -m ray_tpu.scripts.shuffle_bench [--rows N] [--blocks B]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def run_one(strategy: str, rows: int, blocks: int) -> float:
    from ray_tpu import data

    os.environ["RAY_TPU_SHUFFLE_STRATEGY"] = strategy
    try:
        start = time.perf_counter()
        ds = data.range(rows, parallelism=blocks).random_shuffle(seed=0)
        ds.materialize() if hasattr(ds, "materialize") else list(
            ds._execute())
        return time.perf_counter() - start
    finally:
        os.environ.pop("RAY_TPU_SHUFFLE_STRATEGY", None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=200_000)
    p.add_argument("--blocks", type=int, default=16)
    p.add_argument("--nodes", type=int, default=1,
                   help="virtual nodes (fake multi-node cluster); the "
                        "pull shuffle's n_in x n_out fan-out only bites "
                        "with real scheduling spread")
    p.add_argument("--json", default=None)
    args = p.parse_args()

    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=2, num_tpus=0)
    if args.nodes > 1:
        from ray_tpu import api

        for _ in range(args.nodes - 1):
            api._global_node.add_node({"CPU": 2.0})

    # Warmup both paths (worker spawn + import; reducer-pool startup).
    run_one("pull", 1000, 2)
    run_one("push", 1000, args.blocks)
    pull_s = run_one("pull", args.rows, args.blocks)
    push_s = run_one("push", args.rows, args.blocks)
    result = {
        "rows": args.rows,
        "blocks": args.blocks,
        "nodes": args.nodes,
        "pull_seconds": round(pull_s, 3),
        "push_seconds": round(push_s, 3),
        "push_speedup": round(pull_s / push_s, 3),
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
