"""Attention kernel microbench: Pallas flash (fwd + blocked bwd) vs the
XLA reference, train-style (value_and_grad), on the local chip.

Writes BENCH_ATTN JSON: per sequence length, time per step and achieved
attention TFLOP/s for both implementations (causal; FLOPs counted as
3.5 matmuls of 2*S^2*D per head — fwd qk+pv plus bwd dq,dk,dv,dp at
half the causal mask).
"""

from __future__ import annotations

import json
import time


def bench_one(impl: str, batch: int, seq: int, heads: int, d: int,
              iters: int = 10) -> float:
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention, reference_attention

    fn = flash_attention if impl == "flash" else reference_attention
    key = jax.random.PRNGKey(0)
    shape = (batch, seq, heads, d)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), shape,
                                 jnp.bfloat16) for i in range(3))

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v, True).astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(q, k, v)
    jax.block_until_ready(g)
    float(jnp.sum(g[0].astype(jnp.float32)))  # tunnel-safe sync
    t0 = time.perf_counter()
    for _ in range(iters):
        g = step(q, k, v)
    float(jnp.sum(g[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def main(out: str | None = None):
    import jax

    on_tpu = jax.default_backend() != "cpu"
    heads, d = 8, 128
    rows = []
    # Constant token count across lengths: batch*seq = 2^15.
    for seq in ((1024, 2048, 4096, 8192) if on_tpu else (256,)):
        batch = max(1, (1 << 15) // seq) if on_tpu else 2
        # causal attention matmul FLOPs: fwd 2 (qk, pv) + bwd 5
        # (recompute qk, dv, dp, ds->dq, ds->dk) halved by the mask.
        flops = 7 * 2 * batch * heads * seq * seq * d / 2
        row = {"seq": seq, "batch": batch}
        for impl in ("flash", "xla"):
            try:
                dt = bench_one(impl, batch, seq, heads, d)
            except Exception as e:  # XLA OOMs at long seq (the point)
                row[f"{impl}_ms"] = None
                row[f"{impl}_error"] = type(e).__name__
                continue
            row[f"{impl}_ms"] = round(dt * 1e3, 2)
            row[f"{impl}_tflops"] = round(flops / dt / 1e12, 1)
        if row.get("xla_ms") and row.get("flash_ms"):
            row["speedup"] = round(row["xla_ms"] / row["flash_ms"], 2)
        rows.append(row)
        print(json.dumps(row))
    result = {"rows": rows, "heads": heads, "head_dim": d,
              "mode": "train (fwd+bwd, causal, bf16)"}
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    a = p.parse_args()
    main(a.out)
