"""Command-line interface (reference: python/ray/scripts/scripts.py —
`ray start/status/timeline/list/submit/microbenchmark`).

Invoke as ``python -m ray_tpu <command>``. Commands attach to the
running cluster via the current-cluster file (ray_tpu.init(address=
"auto")) except ``start`` which creates one.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time


def cmd_start(args):
    if not args.block:
        # The head lives in-process; without --block, daemonize by
        # re-execing ourselves into a detached --block process (the
        # reference `ray start` launches long-lived daemons the same
        # way this CLI can't: out-of-process).
        import subprocess

        cmd = [sys.executable, "-m", "ray_tpu", "start", "--block"]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        proc = subprocess.Popen(cmd, start_new_session=True)
        from ray_tpu.api import ADDRESS_FILE

        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                with open(ADDRESS_FILE) as f:
                    addr = f.read().strip()
                break
            except FileNotFoundError:
                time.sleep(0.2)
        else:
            print("head did not come up in 60s", file=sys.stderr)
            sys.exit(1)
        print(f"ray_tpu head started at {addr} (pid {proc.pid})")
        print("attach with ray_tpu.init(address='auto'); stop with "
              f"`kill {proc.pid}`")
        return

    import ray_tpu

    ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    from ray_tpu import api

    addr = f"127.0.0.1:{api._global_node.port}"
    print(f"ray_tpu head started at {addr}", flush=True)
    stop = {"flag": False}

    def on_sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_sig)
    signal.signal(signal.SIGTERM, on_sig)
    while not stop["flag"]:
        time.sleep(0.5)
    ray_tpu.shutdown()
    print("head stopped")


def _attach():
    import ray_tpu

    ray_tpu.init(address="auto")
    return ray_tpu


def cmd_status(args):
    ray_tpu = _attach()
    if getattr(args, "watch", False):
        interval = max(0.2, getattr(args, "interval", 2.0))
        try:
            while True:
                # ANSI clear + home: a live top-style surface, not a
                # scrolling log.
                print("\x1b[2J\x1b[H", end="")
                print(f"ray_tpu status  "
                      f"{time.strftime('%H:%M:%S')}  "
                      f"(refresh {interval:g}s, ctrl-c to stop)")
                _print_status(ray_tpu)
                time.sleep(interval)
        except KeyboardInterrupt:  # lint: allow-silent(ctrl-c is the watch loop's exit gesture)
            pass
        finally:
            ray_tpu.shutdown()
        return
    _print_status(ray_tpu)
    ray_tpu.shutdown()


def _print_status(ray_tpu):
    from ray_tpu.util import state as ust

    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print("== cluster resources ==")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0.0):g}/{total[k]:g} available")
    nodes = ust.list_nodes()
    alive = [n for n in nodes if n["state"] == "ALIVE"]
    print(f"== nodes: {len(alive)} alive / {len(nodes)} total ==")
    workers = ust.list_workers()
    by_state = {}
    for w in workers:
        by_state[w["state"]] = by_state.get(w["state"], 0) + 1
    print(f"== workers: {by_state} ==")
    objs = ust.summarize_objects()
    if objs:
        parts = ", ".join(
            f"{st}: {d['count']} ({d['bytes']} B)"
            for st, d in sorted(objs.items()))
        print(f"== objects: {parts} ==")
    asc = ust._call("autoscaler_status")
    if asc.get("enabled"):
        summary = asc.get("last_summary", {})
        cluster = asc.get("cluster", {})
        print("== autoscaler ==")
        print(f"  running: {asc.get('running')}  "
              f"tick: {summary.get('tick', 0)}  "
              f"pending demand: {summary.get('pending_demand', 0)}")
        print(f"  instances: {cluster.get('by_status', {})}")
        if asc.get("last_error"):
            print(f"  last error: {asc['last_error']}")
    from ray_tpu.util import metrics as um

    try:
        merged = um.collect_metrics()
    except Exception:
        merged = {}
    builtin = {n: d for n, d in merged.items()
               if n.startswith("ray_tpu_")}
    if builtin:
        print(f"== metrics: {len(builtin)} ray_tpu_* series "
              f"(`python -m ray_tpu metrics` for detail) ==")
        for name, data in sorted(builtin.items()):
            if data["type"] == "counter":
                total = sum(data["values"].values())
                print(f"  {name}: {total:g}")
    try:
        reply = ust._call("alerts")
    except Exception:
        reply = {}
    if reply.get("enabled"):
        firing = reply.get("firing", [])
        if firing:
            print(f"== alerts: {len(firing)} FIRING ==")
            for f in firing:
                tags = ",".join(f"{k}={v}"
                                for k, v in sorted(f["tags"].items()))
                print(f"  [{f.get('severity', 'warn').upper()}] "
                      f"{f['rule']} {{{tags}}} value={f.get('value'):g}")
        else:
            print("== alerts: none firing ==")


def cmd_summary(args):
    ray_tpu = _attach()
    from ray_tpu.util import state as ust

    print(json.dumps({
        "tasks": ust.summarize_tasks(),
        "actors": ust.summarize_actors(),
        "objects": ust.summarize_objects(),
    }, indent=2))
    ray_tpu.shutdown()


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024.0 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GB"


def _render_hotrpc(snap, top: int = 20) -> list:
    """Pure renderer for `ray_tpu debug hotrpc` (testable without a
    tty): per-handler server-side accounting, top talkers, event-loop
    lag, and pubsub/KV amplification factors."""
    def ms(v) -> str:
        # Percentiles are None until a row has observations.
        return f"{v * 1e3:.1f}ms" if v is not None else "?"

    lines = []
    methods = snap.get("methods", [])
    busy = [m for m in methods if m.get("calls")]
    lines.append(
        f"== handlers: {len(methods)} tracked, {len(busy)} active "
        f"(window {snap.get('since_s', 0):.0f}s, "
        f"talker cap {snap.get('entry_cap')}"
        + (f", overflow {snap['overflow']}" if snap.get("overflow")
           else "") + ") ==")
    hdr = (f"  {'method':<26} {'calls':>7} {'err':>5} "
           f"{'p50':>8} {'p99':>8} {'max':>8} {'q.p99':>8} "
           f"{'in':>9} {'out':>9}")
    lines.append(hdr)
    for m in busy[:top]:
        lines.append(
            f"  {m['method']:<26} {m['calls']:>7} {m['errors']:>5} "
            f"{ms(m.get('handler_p50_s')):>8} "
            f"{ms(m.get('handler_p99_s')):>8} "
            f"{ms(m.get('handler_max_s')):>8} "
            f"{ms(m.get('queue_wait_p99_s')):>8} "
            f"{_fmt_bytes(m.get('recv_bytes')):>9} "
            f"{_fmt_bytes(m.get('reply_bytes')):>9}")
    idle = len(methods) - len(busy)
    if idle:
        lines.append(f"  ... {idle} registered handler(s) with no "
                     "calls yet")
    talkers = snap.get("talkers", [])
    if talkers:
        lines.append(f"top talkers (method x caller, {len(talkers)}):")
        for t in talkers[:top]:
            lines.append(
                f"  {t['method']:<26} {t['caller']:<8} "
                f"calls={t['calls']} "
                f"time={t['handler_s'] * 1e3:.1f}ms "
                f"in={_fmt_bytes(t.get('recv_bytes'))}")
    loops = snap.get("loops", [])
    if loops:
        lines.append("event-loop lag (this process):")
        for lp in loops:
            lines.append(
                f"  {lp['loop']:<14} ticks={lp['ticks']} "
                f"p50={ms(lp.get('lag_p50_s'))} "
                f"p99={ms(lp.get('lag_p99_s'))} "
                f"max={ms(lp.get('lag_max_s'))} "
                f"stalls={lp['stalls']}")
    cluster = snap.get("loop_lag_cluster", [])
    if cluster:
        lines.append("event-loop lag (cluster, from metrics history):")
        for row in cluster:
            proc = row.get("tags", {}).get("proc", "?")
            p50 = row.get("p50_s")
            p99 = row.get("p99_s")
            p50s = f"{p50 * 1e3:.1f}ms" if p50 is not None else "?"
            p99s = f"{p99 * 1e3:.1f}ms" if p99 is not None else "?"
            lines.append(f"  {proc:<28} p50={p50s} p99={p99s}")
    amp = snap.get("amplification", {})
    pubsub = amp.get("pubsub", [])
    if pubsub:
        lines.append(
            "pubsub fan-out (per channel):"
            + (f"  [{amp.get('pruned_total')} dead subscriber(s) "
               "pruned]" if amp.get("pruned_total") else ""))
        for ch in pubsub:
            lines.append(
                f"  {ch['channel']:<26} publishes={ch['publishes']} "
                f"messages={ch['messages']} "
                f"bytes={_fmt_bytes(ch['bytes'])} "
                f"fanout={ch['fanout']} "
                f"(avg {ch['fanout_avg']:.1f})"
                + (f" drops={ch['drops_pruned']}"
                   if ch.get("drops_pruned") else ""))
    kv = amp.get("kv", [])
    if kv:
        lines.append("kv write amplification (per namespace):")
        for ns in kv:
            lines.append(
                f"  {ns['ns']:<26} puts={ns['puts']} "
                f"bytes={_fmt_bytes(ns['bytes'])} -> "
                f"{_fmt_bytes(ns['amplified_bytes'])} on the wire "
                f"(x{ns['amplification']:.1f})")
    if not busy and not pubsub and not kv:
        lines.append("no RPC traffic recorded yet")
    return lines


def cmd_debug(args):
    ray_tpu = _attach()
    from ray_tpu.util import debug as udebug

    try:
        if args.debug_cmd == "hotrpc":
            from ray_tpu.util.state import _call

            snap = _call("rpc_stats", {"top": args.top,
                                       "window_s": args.window})
            for line in _render_hotrpc(snap, top=args.top):
                print(line)
        elif args.debug_cmd == "stacks":
            for source, threads in sorted(
                    udebug.cluster_stacks(args.timeout).items()):
                print(f"==== {source} ====")
                for thread, frames in threads.items():
                    print(f"--- {thread} ---")
                    for line in frames:
                        print(line)
                print()
        elif args.debug_cmd == "dump":
            manifest = udebug.write_debug_bundle(args.out,
                                                timeout_s=args.timeout)
            print(f"wrote debug bundle to {args.out}")
            print(f"  sources: {len(manifest['sources'])} "
                  f"({', '.join(manifest['sources'])})")
            print(f"  nodes: {len(manifest['nodes'])}")
            if manifest["errors"]:
                print(f"  partial sections: "
                      f"{json.dumps(manifest['errors'])}")
        else:  # why
            print(udebug.why(args.kind, args.id,
                             timeout_s=args.timeout))
    finally:
        ray_tpu.shutdown()


def cmd_profile(args):
    """Live profiling plane: fan the sampling profiler out over the
    cluster (reference: the dashboard's py-spy capture buttons /
    `ray stack`, as a CLI) and write folded stacks + flamegraph HTML."""
    ray_tpu = _attach()
    from ray_tpu.util import profiler

    kind = "all" if args.kind == "cluster" else args.kind
    if kind != "all" and not args.id:
        print(f"profile {args.kind} requires an id", file=sys.stderr)
        sys.exit(2)
    if getattr(args, "device", False):
        # --device flips to the device-trace plane; the host-sampler
        # default below is unchanged.
        _cmd_profile_device(ray_tpu, kind, args)
        return
    try:
        print(f"sampling {args.kind} "
              f"{args.id or ''} for {args.duration:g}s at "
              f"{args.hz:g} Hz ...", flush=True)
        reply = profiler.capture_cluster(
            kind, args.id, duration_s=args.duration, hz=args.hz)
        if reply.get("error"):
            print(f"error: {reply['error']}", file=sys.stderr)
            sys.exit(1)
        manifest = profiler.write_profile_outputs(
            reply, args.out,
            title=f"ray_tpu profile {args.kind} {args.id or ''}".strip())
        print(f"wrote profile to {args.out} "
              f"({manifest['samples']} samples from "
              f"{len(manifest['sources'])} process(es))")
        print(f"  flamegraph: {manifest['flamegraph']}")
        buckets = sorted(manifest["tasks"].items(),
                         key=lambda kv: -kv[1].get("samples", 0))
        for ident, bucket in buckets[:10]:
            print(f"  {bucket.get('samples', 0):>6} samples  "
                  f"{bucket.get('name', '?')} ({ident}) "
                  f"on {bucket.get('source', '?')}")
        if manifest["errors"]:
            print(f"  unreachable: {json.dumps(manifest['errors'])}")
    finally:
        ray_tpu.shutdown()


def _cmd_profile_device(ray_tpu, kind, args):
    """Device-trace plane: fan a bounded jax.profiler window out over
    the cluster and write per-source trace.json.gz + ops.json plus a
    merged host+device timeline HTML."""
    from ray_tpu.util import device_trace

    try:
        print(f"device-tracing {args.kind} {args.id or ''} for "
              f"{args.duration:g}s ...", flush=True)
        reply = device_trace.capture_cluster(
            kind, args.id, duration_s=args.duration)
        if reply.get("error"):
            print(f"error: {reply['error']}", file=sys.stderr)
            sys.exit(1)
        manifest = device_trace.write_trace_outputs(
            reply, args.out,
            title=(f"ray_tpu profile --device {args.kind} "
                   f"{args.id or ''}").strip())
        print(f"wrote device trace to {args.out} "
              f"({manifest['device_events']} device op event(s) from "
              f"{len(manifest['sources'])} process(es))")
        print(f"  timeline: {manifest['timeline']}")
        for row in manifest["steps"][:12]:
            ops = ", ".join(f"{name} {us / 1e3:.1f}ms"
                            for name, us in row.get("top_ops", [])[:3])
            print(f"  rank {row.get('rank')} step {row.get('step')}: "
                  f"compile {row.get('compile_ms', 0):.1f}ms "
                  f"execute {row.get('execute_ms', 0):.1f}ms "
                  f"gap {row.get('gap_ms', 0):.1f}ms"
                  + (f"  [{ops}]" if ops else ""))
        if len(manifest["steps"]) > 12:
            print(f"  ... {len(manifest['steps']) - 12} more step "
                  "row(s) in trace.json")
        if manifest["errors"]:
            print(f"  failed: {json.dumps(manifest['errors'])}")
    finally:
        ray_tpu.shutdown()


def cmd_list(args):
    ray_tpu = _attach()
    from ray_tpu.util import state as ust

    fn = {
        "actors": ust.list_actors,
        "tasks": ust.list_tasks,
        "nodes": ust.list_nodes,
        "workers": ust.list_workers,
        "objects": ust.list_objects,
        "jobs": ust.list_jobs,
        "placement-groups": ust.list_placement_groups,
    }[args.kind]
    print(json.dumps(fn(), indent=2, default=str))
    ray_tpu.shutdown()


def _fmt_tags(tk) -> str:
    if not tk:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in tk) + "}"


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 60) -> str:
    """Render a value series as a unicode sparkline (pure; testable)."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        # Evenly resample down to the display width.
        step = len(vals) / width
        vals = [vals[min(len(vals) - 1, int(i * step))]
                for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    top = len(_SPARK_CHARS) - 1
    return "".join(_SPARK_CHARS[int((v - lo) / span * top)]
                   for v in vals)


def _render_history(reply, window_s: float) -> list:
    """Pure renderer for `ray_tpu metrics --history <name>` output."""
    lines = []
    if not reply.get("enabled", True):
        return ["metrics history disabled "
                "(RAY_TPU_METRICS_HISTORY_ENABLED=0)"]
    series = reply.get("series", [])
    if not series:
        return [f"no history for {reply.get('name', '?')} "
                f"in the last {window_s:g}s"]
    for s in series:
        pts = s.get("points", [])
        vals = [p[1] for p in pts]
        tags = ",".join(f"{k}={v}"
                        for k, v in sorted(s.get("tags", {}).items()))
        stale = "" if s.get("fresh", True) else "  [STALE]"
        head = f"{{{tags}}}" if tags else "(no tags)"
        lines.append(f"{head} ({s.get('kind', '?')}, "
                     f"{len(pts)} points){stale}")
        if vals:
            lines.append(f"  {_sparkline(vals)}")
            lines.append(f"  min={min(vals):g} max={max(vals):g} "
                         f"last={vals[-1]:g}")
    for agg_row in reply.get("aggregates", []):
        tags = ",".join(f"{k}={v}" for k, v in
                        sorted(agg_row.get("tags", {}).items()))
        lines.append(f"{reply.get('agg')}[{window_s:g}s]"
                     f"{{{tags}}} = {agg_row.get('value'):g}")
    return lines


def cmd_metrics(args):
    """Merged cluster metrics snapshot (reference: the dashboard's
    Prometheus scrape, as a one-shot CLI); ``--history <name>`` renders
    the head-side time-series as sparklines instead."""
    ray_tpu = _attach()
    from ray_tpu.util import metrics as um

    if getattr(args, "history", None):
        from ray_tpu.util.state import _call

        payload = {"name": args.history, "window_s": args.window}
        if getattr(args, "agg", None):
            payload["agg"] = args.agg
        reply = _call("metrics_history", payload)
        print(f"{args.history} — last {args.window:g}s")
        for line in _render_history(reply, args.window):
            print(line)
        ray_tpu.shutdown()
        return
    if args.format == "prometheus":
        print(um.prometheus_text(), end="")
        ray_tpu.shutdown()
        return
    detailed = um.collect_metrics_detailed()
    merged = detailed["merged"]
    stale = detailed["stale"]
    procs = detailed["procs"]
    if procs:
        parts = []
        for p in procs:
            age = (f"{p['age_s']:.1f}s" if p.get("age_s") is not None
                   else "age unknown")
            parts.append(f"{p['proc']} {age}"
                         + (" STALE" if p.get("stale") else ""))
        n_stale = sum(1 for p in procs if p.get("stale"))
        print(f"== snapshots: {len(procs)} procs"
              + (f", {n_stale} stale" if n_stale else "") + " ==")
        for part in parts:
            print(f"  {part}")
    if not merged:
        print("no metrics reported yet")
    for name, data in sorted(merged.items()):
        print(f"{name} ({data['type']})"
              + (f" — {data['description']}" if data.get("description")
                 else ""))
        stale_series = set(map(tuple, stale.get(name, ())))
        if data["type"] == "histogram":
            for tk, h in sorted(data["values"].items()):
                count, total = h[-1], h[-2]
                mean_ms = (total / count * 1e3) if count else 0.0
                print(f"  {_fmt_tags(tk) or '(no tags)'}: "
                      f"count={count} mean={mean_ms:.2f}ms")
        else:
            for tk, v in sorted(data["values"].items()):
                flag = "  [STALE]" if tk in stale_series else ""
                print(f"  {_fmt_tags(tk) or '(no tags)'}: {v:g}{flag}")
    ray_tpu.shutdown()


def _render_alerts(reply, limit: int = 20) -> list:
    """Pure renderer for `ray_tpu alerts` (testable without a tty)."""
    lines = []
    if not reply.get("enabled", True):
        return ["alert engine disabled (RAY_TPU_ALERTS_ENABLED=0)"]

    def fmt_tags(tags):
        inner = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
        return f"{{{inner}}}" if inner else ""

    def fmt_ts(ts):
        return (time.strftime("%H:%M:%S", time.localtime(ts))
                if ts else "?")

    firing = reply.get("firing", [])
    if firing:
        lines.append(f"FIRING ({len(firing)}):")
        for f in firing:
            lines.append(
                f"  [{f.get('severity', 'warn').upper()}] {f['rule']} "
                f"{fmt_tags(f.get('tags', {}))} "
                f"value={f.get('value'):g} "
                f"since {fmt_ts(f.get('fired_ts'))}")
    else:
        lines.append("FIRING: none")
    episodes = reply.get("episodes", [])[:limit]
    if episodes:
        lines.append(f"recent episodes (newest first, {len(episodes)}"
                     f" of {len(reply.get('episodes', []))}):")
        for ep in episodes:
            state = ("resolved " + fmt_ts(ep.get("resolved_ts"))
                     if ep.get("resolved_ts") else "STILL FIRING")
            vals = [p[1] for p in ep.get("evidence", [])]
            spark = f"  {_sparkline(vals, width=24)}" if vals else ""
            lines.append(
                f"  {fmt_ts(ep.get('fired_ts'))} {ep['rule']} "
                f"{fmt_tags(ep.get('tags', {}))} "
                f"value={ep.get('value'):g} -> {state}{spark}")
    rules = reply.get("rules", [])
    lines.append(f"rules: {len(rules)} loaded "
                 f"({', '.join(r['name'] for r in rules)})")
    return lines


def cmd_alerts(args):
    """SLO/alert state from the head's cluster health plane."""
    ray_tpu = _attach()
    from ray_tpu.util.state import _call

    try:
        if getattr(args, "rules", False):
            reply = _call("alerts")
            print(json.dumps(reply.get("rules", []), indent=2))
            return
        reply = _call("alerts")
        for line in _render_alerts(reply, limit=args.limit):
            print(line)
    finally:
        ray_tpu.shutdown()


def cmd_timeline(args):
    ray_tpu = _attach()
    from ray_tpu.util import timeline

    events = timeline(args.output)
    print(f"wrote {len(events)} spans to {args.output}")
    ray_tpu.shutdown()


def cmd_submit(args):
    import ray_tpu
    from ray_tpu.job import JobSubmissionClient

    import shlex

    entrypoint = list(args.entrypoint)
    if entrypoint and entrypoint[0] == "--":
        entrypoint = entrypoint[1:]
    if not entrypoint:
        print("no entrypoint given", file=sys.stderr)
        sys.exit(2)
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=shlex.join(entrypoint),
        runtime_env={"working_dir": args.working_dir}
        if args.working_dir else None)
    print(f"submitted job {job_id}")
    if args.wait:
        status = client.wait_until_finish(job_id, timeout=args.timeout)
        print(f"job {job_id}: {status}")
        print(client.get_job_logs(job_id))
        ray_tpu.shutdown()
        sys.exit(0 if status == "SUCCEEDED" else 1)
    ray_tpu.shutdown()


def cmd_microbenchmark(args):
    from ray_tpu.scripts import microbenchmark

    microbenchmark.main()


def cmd_lint(args):
    """Static-analysis suite (tools/analysis): no cluster needed."""
    from ray_tpu.tools.analysis import runner

    argv = list(args.lint_args)
    if args.as_json:
        argv.append("--json")
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.baseline:
        argv += ["--baseline", args.baseline]
    sys.exit(runner.main(argv))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ray-tpu", description="ray_tpu cluster CLI")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head node")
    p.add_argument("--head", action="store_true", default=True)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--block", action="store_true",
                   help="stay in the foreground until SIGINT/SIGTERM")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster resource status")
    p.add_argument("--watch", action="store_true",
                   help="refresh continuously (top-style) until ctrl-c")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval for --watch (seconds)")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("summary", help="task/actor summaries")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("kind", choices=["actors", "tasks", "nodes", "workers",
                                    "objects", "jobs",
                                    "placement-groups"])
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("metrics", help="merged cluster metrics snapshot")
    p.add_argument("--format", choices=["summary", "prometheus"],
                   default="summary")
    p.add_argument("--history", metavar="NAME", default=None,
                   help="render the head-side time-series for one "
                   "metric as sparklines instead of the snapshot")
    p.add_argument("--window", type=float, default=600.0,
                   help="history window in seconds (with --history)")
    p.add_argument("--agg", default=None,
                   help="also print a window aggregate (delta/rate/"
                   "max/avg/p99/... per the metric kind)")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "alerts", help="SLO alert state: firing now + recent "
        "fire/resolve episodes with series evidence")
    p.add_argument("--limit", type=int, default=20,
                   help="episodes to show")
    p.add_argument("--rules", action="store_true",
                   help="dump the loaded rule set as JSON")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser("timeline", help="dump chrome-tracing timeline")
    p.add_argument("--output", "-o", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "debug", help="flight recorder / debug-dump plane")
    dsub = p.add_subparsers(dest="debug_cmd", required=True)
    d = dsub.add_parser("stacks",
                        help="live stacks of every process")
    d.add_argument("--timeout", type=float, default=5.0)
    d.set_defaults(fn=cmd_debug)
    d = dsub.add_parser(
        "dump", help="write a cluster-wide debug bundle "
        "(rings + stacks + state tables + metrics + timeline)")
    d.add_argument("--out", "-o", default="ray_tpu_debug")
    d.add_argument("--timeout", type=float, default=10.0)
    d.set_defaults(fn=cmd_debug)
    d = dsub.add_parser(
        "hotrpc", help="control-plane load observatory: per-handler "
        "server-side timings, top talkers, event-loop lag, and "
        "pubsub/KV amplification factors")
    d.add_argument("--top", type=int, default=20,
                   help="rows to show per table")
    d.add_argument("--window", type=float, default=300.0,
                   help="cluster loop-lag aggregation window (seconds)")
    d.set_defaults(fn=cmd_debug)
    d = dsub.add_parser(
        "why", help="explain why a task/actor/object/placement-group "
        "is in its state")
    d.add_argument("kind", choices=["task", "actor", "object",
                                    "placement-group"])
    d.add_argument("id", help="full or prefix hex id")
    d.add_argument("--timeout", type=float, default=5.0)
    d.set_defaults(fn=cmd_debug)

    p = sub.add_parser(
        "profile", help="on-demand cluster sampling profiler "
        "(folded stacks + flamegraph HTML, task-attributed)")
    p.add_argument("kind", choices=["worker", "task", "actor",
                                    "cluster"],
                   help="what to sample: one worker, the worker "
                   "running a task, an actor's worker, or every "
                   "process")
    p.add_argument("id", nargs="?", default=None,
                   help="full or prefix hex id (not needed for "
                   "'cluster')")
    p.add_argument("--duration", type=float, default=10.0,
                   help="sampling window in seconds")
    p.add_argument("--hz", type=float, default=100.0,
                   help="sampling rate")
    p.add_argument("--out", "-o", default="ray_tpu_profile",
                   help="output directory")
    p.add_argument("--device", action="store_true",
                   help="capture a jax.profiler device trace instead "
                   "of the host sampler: per-source trace.json.gz + "
                   "parsed op table + merged host+device timeline "
                   "HTML with per-step compile/execute breakdown")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("submit", help="submit a job")
    p.add_argument("--working-dir", default=None)
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600)
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("microbenchmark", help="run the perf suite")
    p.set_defaults(fn=cmd_microbenchmark)

    p = sub.add_parser(
        "lint", help="concurrency/static-analysis suite "
        "(lock discipline, async hygiene, silent catches, config flags) "
        "against the ratchet baseline")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--update-baseline", action="store_true",
                   help="bank fixed violations / re-pin the baseline")
    p.add_argument("--baseline", default=None,
                   help="baseline path ('none' disables)")
    p.add_argument("lint_args", nargs="*",
                   help="optional file paths relative to the package")
    p.set_defaults(fn=cmd_lint)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
