"""Serve streaming benchmark: TTFT, inter-chunk latency, aggregate
chunk throughput at N concurrent streams.

The serving-quality metrics that matter for LLM token streaming
(reference: TTFT / inter-token latency in the TPU serving comparison
literature) — measured through the full handle path (router ->
replica's streaming lane -> core stream_item delivery) so the numbers
cover the real stack, not a mocked generator. Writes
``BENCH_SERVE_STREAM.json`` via ``--json``; also importable
(``run(...)``)."""

from __future__ import annotations

import statistics
import threading
import time
from typing import Dict, List


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def run(num_streams: int = 8, chunks_per_stream: int = 200,
        chunk_interval_s: float = 0.0, init: bool = True) -> Dict[str, float]:
    import ray_tpu
    from ray_tpu import serve

    if init and not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, num_tpus=0)

    @serve.deployment(num_cpus=0.5, max_queued_stream_chunks=64)
    class TokenGen:
        async def __call__(self, n_and_delay):
            import asyncio

            n, delay = n_and_delay
            for i in range(n):
                if delay:
                    await asyncio.sleep(delay)
                yield i

    h = serve.run(TokenGen.bind(), name="stream_bench", proxy=False)

    # Warm the replica (first stream pays import/jit costs).
    list(h.options(stream=True).remote((3, 0.0)))

    ttfts: List[float] = []
    gaps: List[float] = []
    counts: List[int] = []
    lock = threading.Lock()

    def consume():
        t0 = time.perf_counter()
        gen = h.options(stream=True).remote(
            (chunks_per_stream, chunk_interval_s))
        last = None
        ttft = None
        local_gaps = []
        n = 0
        for _chunk in gen:
            now = time.perf_counter()
            if ttft is None:
                ttft = now - t0
            if last is not None:
                local_gaps.append(now - last)
            last = now
            n += 1
        with lock:
            if ttft is not None:
                ttfts.append(ttft)
            gaps.extend(local_gaps)
            counts.append(n)

    threads = [threading.Thread(target=consume)
               for _ in range(num_streams)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0

    total_chunks = sum(counts)
    gaps.sort()
    results = {
        "concurrent_streams": float(num_streams),
        "chunks_per_stream": float(chunks_per_stream),
        "ttft_p50_ms": statistics.median(ttfts) * 1e3 if ttfts else 0.0,
        "ttft_p99_ms": _percentile(sorted(ttfts), 0.99) * 1e3,
        "inter_chunk_p50_ms": statistics.median(gaps) * 1e3
        if gaps else 0.0,
        "inter_chunk_p99_ms": _percentile(gaps, 0.99) * 1e3,
        "chunks_per_second": total_chunks / elapsed if elapsed else 0.0,
    }
    for name, value in results.items():
        print(f"{name}: {value:,.2f}")
    serve.delete("stream_bench")
    return results


def main():
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None,
                   help="also write results as JSON to this path")
    p.add_argument("--streams", type=int, default=8)
    p.add_argument("--chunks", type=int, default=200)
    args = p.parse_args()
    results = run(num_streams=args.streams, chunks_per_stream=args.chunks)
    if args.json:
        import json

        with open(args.json, "w") as f:
            json.dump({k: round(v, 3) for k, v in results.items()}, f,
                      indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
