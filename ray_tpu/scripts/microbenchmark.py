"""Core microbenchmarks (reference: _private/ray_perf.py — the
`ray microbenchmark` suite: task/actor throughput, put/get bandwidth).
Prints one line per benchmark; also importable (run_all)."""

from __future__ import annotations

import time
from typing import Dict

import numpy as np


def _timeit(name: str, fn, multiplier: int = 1,
            duration: float = 2.0) -> float:
    # Warmup.
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    elapsed = time.perf_counter() - start
    rate = count * multiplier / elapsed
    print(f"{name}: {rate:,.1f} /s")
    return rate


def run_all(init: bool = True) -> Dict[str, float]:
    import ray_tpu

    if init and not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, num_tpus=0)
    results: Dict[str, float] = {}
    # Debug bisect knob: RAY_TPU_MB_SKIP=tasks,actor,putget skips
    # sections (used to isolate cross-section interference).
    import os as _os

    _skip = set(filter(None, _os.environ.get(
        "RAY_TPU_MB_SKIP", "").split(",")))

    @ray_tpu.remote
    def tiny(x):
        return x

    # Warm the worker pool to its steady state FIRST: a worker spawn
    # costs seconds of import CPU (ray_tpu + jax) on a small host, and a
    # background import competing for the core poisons every number
    # below — most brutally the µs-scale channel latency, where each
    # semaphore wakeup then eats a full scheduler rotation (~8ms).
    @ray_tpu.remote
    def _warm():
        import time as _t

        _t.sleep(0.5)
        return 1

    ray_tpu.get([_warm.remote() for _ in range(4)], timeout=180)
    time.sleep(2)  # prestart replacements finish importing

    # single-client task throughput (async submission, batched get)
    N = 100

    def tasks_batch():
        ray_tpu.get([tiny.remote(i) for i in range(N)], timeout=120)

    if "tasks" not in _skip:
        results["tasks_per_second"] = _timeit(
            "single-client tasks", tasks_batch, multiplier=N)

    class Counter:
        def __init__(self):
            self.v = 0

        def inc(self):
            self.v += 1
            return self.v

    actor = ray_tpu.remote(Counter).options(num_cpus=0.5).remote()
    ray_tpu.get(actor.inc.remote(), timeout=60)

    def actor_sync():
        ray_tpu.get(actor.inc.remote(), timeout=60)

    if "actor" not in _skip:
        results["actor_calls_sync_per_second"] = _timeit(
            "1:1 actor calls sync", actor_sync)

    def actor_async_batch():
        ray_tpu.get([actor.inc.remote() for _ in range(N)], timeout=120)

    if "actor" not in _skip:
        results["actor_calls_async_per_second"] = _timeit(
            "1:1 actor calls async", actor_async_batch, multiplier=N)

    # put/get bandwidth on 10MB arrays through the shm arena
    data = np.random.default_rng(0).random(10 * 1024 * 1024 // 8)

    def put_get():
        ref = ray_tpu.put(data)
        out = ray_tpu.get(ref, timeout=60)
        assert out.shape == data.shape

    if "putget" not in _skip:
        rate = _timeit("10MB put+get roundtrips", put_get)
        results["put_gigabytes_per_second"] = rate * 10 / 1024 * 2
        print(f"object store bandwidth: "
              f"{results['put_gigabytes_per_second']:.2f} GiB/s")

    # compiled-DAG channel path vs the task path (reference:
    # compiled_dag_node.py's raison d'être — p50, since the channel hop
    # is microseconds while scheduler noise is milliseconds)
    import statistics

    from ray_tpu.dag import InputNode

    # Let the put/get bench's ~GBs of dead refs finish freeing (arena
    # deletes + free RPCs drain on the driver loop thread and would
    # poison a microsecond-scale latency measurement with GIL stalls).
    time.sleep(3)

    def actor_sync_once():
        ray_tpu.get(actor.inc.remote(), timeout=60)

    lats = []
    for _ in range(300):
        t0 = time.perf_counter()
        actor_sync_once()
        lats.append(time.perf_counter() - t0)
    task_p50 = statistics.median(lats)
    # Echo DAG on a dedicated actor (Counter.inc takes no arg).

    @ray_tpu.remote
    class _Echo:
        def fwd(self, x):
            return x

    echo = _Echo.options(num_cpus=0.01).remote()
    ray_tpu.get(echo.fwd.remote(0), timeout=60)
    cd = echo.fwd.bind(InputNode()).experimental_compile()
    cd.execute(0, timeout=60)
    lats = []
    for i in range(300):
        t0 = time.perf_counter()
        cd.execute(i, timeout=60)
        lats.append(time.perf_counter() - t0)
    cd.teardown()
    compiled_p50 = statistics.median(lats)
    results["compiled_dag_p50_us"] = compiled_p50 * 1e6
    results["compiled_dag_speedup_vs_task_path"] = task_p50 / compiled_p50
    srt = sorted(lats)
    print(f"compiled dag p50: {compiled_p50*1e6:.0f}us "
          f"(p10 {srt[len(srt)//10]*1e6:.0f} "
          f"p90 {srt[9*len(srt)//10]*1e6:.0f}) vs task-path "
          f"{task_p50*1e6:.0f}us "
          f"({results['compiled_dag_speedup_vs_task_path']:.1f}x)")
    ray_tpu.kill(actor)
    return results


def main():
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None,
                   help="also write results as JSON to this path")
    args = p.parse_args()
    results = run_all()
    if args.json:
        import json

        with open(args.json, "w") as f:
            json.dump({k: round(v, 1) for k, v in results.items()}, f,
                      indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
