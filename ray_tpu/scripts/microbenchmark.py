"""Core microbenchmarks (reference: _private/ray_perf.py — the
`ray microbenchmark` suite: task/actor throughput, put/get bandwidth).
Prints one line per benchmark; also importable (run_all)."""

from __future__ import annotations

import time
from typing import Dict

import numpy as np


def _timeit(name: str, fn, multiplier: int = 1,
            duration: float = 2.0) -> float:
    # Warmup.
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    elapsed = time.perf_counter() - start
    rate = count * multiplier / elapsed
    print(f"{name}: {rate:,.1f} /s")
    return rate


def run_all(init: bool = True) -> Dict[str, float]:
    import ray_tpu

    if init and not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, num_tpus=0)
    results: Dict[str, float] = {}

    @ray_tpu.remote
    def tiny(x):
        return x

    # single-client task throughput (async submission, batched get)
    N = 100

    def tasks_batch():
        ray_tpu.get([tiny.remote(i) for i in range(N)], timeout=120)

    results["tasks_per_second"] = _timeit(
        "single-client tasks", tasks_batch, multiplier=N)

    class Counter:
        def __init__(self):
            self.v = 0

        def inc(self):
            self.v += 1
            return self.v

    actor = ray_tpu.remote(Counter).options(num_cpus=0.5).remote()
    ray_tpu.get(actor.inc.remote(), timeout=60)

    def actor_sync():
        ray_tpu.get(actor.inc.remote(), timeout=60)

    results["actor_calls_sync_per_second"] = _timeit(
        "1:1 actor calls sync", actor_sync)

    def actor_async_batch():
        ray_tpu.get([actor.inc.remote() for _ in range(N)], timeout=120)

    results["actor_calls_async_per_second"] = _timeit(
        "1:1 actor calls async", actor_async_batch, multiplier=N)

    # put/get bandwidth on 10MB arrays through the shm arena
    data = np.random.default_rng(0).random(10 * 1024 * 1024 // 8)

    def put_get():
        ref = ray_tpu.put(data)
        out = ray_tpu.get(ref, timeout=60)
        assert out.shape == data.shape

    rate = _timeit("10MB put+get roundtrips", put_get)
    results["put_gigabytes_per_second"] = rate * 10 / 1024 * 2
    print(f"object store bandwidth: "
          f"{results['put_gigabytes_per_second']:.2f} GiB/s")
    ray_tpu.kill(actor)
    return results


def main():
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None,
                   help="also write results as JSON to this path")
    args = p.parse_args()
    results = run_all()
    if args.json:
        import json

        with open(args.json, "w") as f:
            json.dump({k: round(v, 1) for k, v in results.items()}, f,
                      indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
