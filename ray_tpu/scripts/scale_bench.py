"""Scale/stress lane (reference: release/benchmarks README — many_tasks,
many_actors, object-store broadcast; release_logs/2.9.1/benchmarks/*).

Three dimensions, recorded per round as BENCH_SCALE_r*.json:
- many tasks: N trivial tasks across a fake multi-node cluster
  (reference envelope: 10k launched at 575/s on 2500 CPUs),
- many actors: M actor creations to readiness (reference: 10k actors
  registered at 647/s on a release cluster),
- broadcast: one 100 MB object read by a task on every node agent
  (reference: 1 GiB to 50 nodes in 74.8 s).

Sizes default to what a single shared core can express (worker spawn
costs ~2s of CPU here; PARITY.md documents the box): the value of the
lane is the round-over-round trend, not the absolute envelope.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def bench_many_tasks(n_tasks: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def nop(i):
        return i

    # Warm the worker pool first so the number measures the task path,
    # not process spawn (reference harness warms too).
    ray_tpu.get([nop.remote(i) for i in range(64)], timeout=300)
    t0 = time.perf_counter()
    refs = [nop.remote(i) for i in range(n_tasks)]
    out = ray_tpu.get(refs, timeout=900)
    dt = time.perf_counter() - t0
    assert out[-1] == n_tasks - 1
    return {"num_tasks": n_tasks, "seconds": round(dt, 2),
            "tasks_per_second": round(n_tasks / dt, 1)}


def bench_many_actors(n_actors: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.01)
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n_actors)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=1800)
    dt = time.perf_counter() - t0
    rate = n_actors / dt
    for a in actors:
        ray_tpu.kill(a)
    return {"num_actors": n_actors, "seconds": round(dt, 2),
            "actors_per_second": round(rate, 2)}


def bench_broadcast(n_agents: int, mb: int, head_port: int) -> dict:
    import numpy as np

    import ray_tpu

    agents = []
    try:
        for i in range(n_agents):
            agents.append(subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.node_agent",
                 "--head-host", "127.0.0.1",
                 "--head-port", str(head_port),
                 "--num-cpus", "1",
                 "--resources", json.dumps({f"bcast{i}": 1}),
                 "--object-store-memory", str(512 << 20)],
                env={**os.environ},
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            res = ray_tpu.cluster_resources()
            if all(res.get(f"bcast{i}") for i in range(n_agents)):
                break
            time.sleep(0.3)
        else:
            raise TimeoutError("broadcast agents never joined")

        data = np.random.default_rng(0).random(mb * (1 << 20) // 8)
        ref = ray_tpu.put(data)

        def reader(arr):
            return float(arr[0]) + arr.nbytes

        def warm():
            return 1

        # Warm worker spawn on each agent WITHOUT touching the object,
        # so the timed round measures exactly one cross-node pull per
        # node (reference: fresh nodes reading one broadcast object).
        ray_tpu.get([ray_tpu.remote(warm).options(
            resources={f"bcast{i}": 1}).remote()
            for i in range(n_agents)], timeout=900)
        t0 = time.perf_counter()
        tasks = [ray_tpu.remote(reader).options(
            resources={f"bcast{i}": 1}).remote(ref)
            for i in range(n_agents)]
        out = ray_tpu.get(tasks, timeout=900)
        dt = time.perf_counter() - t0
        assert all(o == out[0] for o in out)
        return {"num_nodes": n_agents, "mb": mb,
                "broadcast_seconds": round(dt, 2)}
    finally:
        for p in agents:
            p.terminate()
        for p in agents:
            try:
                p.wait(timeout=30)
            except Exception:
                p.kill()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    p.add_argument("--tasks", type=int, default=10_000)
    p.add_argument("--actors", type=int, default=200)
    p.add_argument("--nodes", type=int, default=4,
                   help="virtual scheduling nodes for the task lane")
    p.add_argument("--broadcast-nodes", type=int, default=2,
                   help="real node-agent processes for the broadcast "
                        "lane (each is a full daemon; 1-core box)")
    p.add_argument("--broadcast-mb", type=int, default=100)
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu import api

    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=1 << 30)
    # Fake multi-node: extra virtual nodes so scheduling spreads
    # (reference: cluster_utils.Cluster.add_node).
    for _ in range(args.nodes - 1):
        api._global_node.add_node({"CPU": 8.0})

    results = {}
    try:
        results["many_tasks"] = bench_many_tasks(args.tasks)
        results["many_actors"] = bench_many_actors(args.actors)
        results["broadcast"] = bench_broadcast(
            args.broadcast_nodes, args.broadcast_mb,
            api._global_node.port)
        results["reference_envelope"] = {
            "many_tasks": "10k tasks @ 575/s (2500 CPUs)",
            "many_actors": "10k actors @ 647/s (release cluster)",
            "broadcast": "1 GiB to 50 nodes in 74.8 s",
        }
        print(json.dumps(results, indent=1))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=1)
                f.write("\n")
    finally:
        # Always tear the cluster down: leaked workers/agents poison
        # every later run on this single-core box.
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
