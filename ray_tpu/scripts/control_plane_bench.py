"""Control-plane load lane (`bench.py control-plane`).

Stands up a fake multi-node cluster (virtual scheduling nodes, the
scale-lane trick) and drives the three traffic classes the head's
control plane serves — registration + task/actor churn, pubsub
subscribe/publish churn, KV-put churn — then reads the load
observatory back out (`rpc_stats`) and writes
BENCH_CONTROL_PLANE.json: per-handler p50/p99 server-side timings,
event-loop lag, and pubsub/KV fan-out amplification factors. The
value of the lane is the round-over-round trend in handler latency
and amplification, not the absolute throughput of this box.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _task_churn(n_tasks: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def nop(i):
        return i

    # Warm the worker pool so the churn measures the control plane,
    # not process spawn.
    ray_tpu.get([nop.remote(i) for i in range(32)], timeout=300)
    t0 = time.perf_counter()
    out = ray_tpu.get([nop.remote(i) for i in range(n_tasks)],
                      timeout=900)
    dt = time.perf_counter() - t0
    assert out[-1] == n_tasks - 1
    return {"num_tasks": n_tasks, "seconds": round(dt, 2),
            "tasks_per_second": round(n_tasks / dt, 1)}


def _actor_churn(n_actors: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.01)
    class A:
        def ping(self):
            return 1

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n_actors)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=900)
    dt = time.perf_counter() - t0
    for a in actors:
        ray_tpu.kill(a)
    return {"num_actors": n_actors, "seconds": round(dt, 2),
            "actors_per_second": round(n_actors / dt, 2)}


def _pubsub_churn(n_channels: int, n_publishes: int,
                  n_subscribers: int = 4) -> dict:
    import ray_tpu
    from ray_tpu.util.state import _call

    @ray_tpu.remote(num_cpus=0.01)
    class Sub:
        """Worker-side subscriber: registers this worker's head
        connection on every bench channel so publishes fan out
        across real conns (fanout > 1)."""

        def subscribe(self, channels):
            from ray_tpu.util.state import _call as call

            for ch in channels:
                call("subscribe", {"channel": ch})
            return 1

    channels = [f"bench-cp-{i}" for i in range(n_channels)]
    subs = [Sub.remote() for _ in range(n_subscribers)]
    ray_tpu.get([s.subscribe.remote(channels) for s in subs],
                timeout=300)
    for ch in channels:
        _call("subscribe", {"channel": ch})  # the driver too
    payload = "x" * 512
    t0 = time.perf_counter()
    for i in range(n_publishes):
        _call("publish", {"channel": channels[i % n_channels],
                          "data": {"seq": i, "blob": payload}})
    dt = time.perf_counter() - t0
    # Kill half the subscribers and publish again: the dead conns must
    # be PRUNED from the fan-out sets (counted in the artifact), not
    # notified forever.
    for s in subs[: max(1, n_subscribers // 2)]:
        ray_tpu.kill(s)
    time.sleep(0.5)
    for i, ch in enumerate(channels):
        _call("publish", {"channel": ch,
                          "data": {"seq": n_publishes + i}})
    return {"channels": n_channels, "publishes": n_publishes,
            "subscribers": n_subscribers + 1,
            "seconds": round(dt, 2),
            "publishes_per_second": round(n_publishes / dt, 1)}


def _kv_churn(n_puts: int) -> dict:
    from ray_tpu.util.state import _call

    value = b"v" * 1024
    t0 = time.perf_counter()
    for i in range(n_puts):
        _call("kv_put", {"ns": "bench", "key": f"cp-{i % 64}",
                         "value": value})
    dt = time.perf_counter() - t0
    return {"puts": n_puts, "seconds": round(dt, 2),
            "puts_per_second": round(n_puts / dt, 1)}


def _summarize(snap: dict, top: int) -> dict:
    """Distill an rpc_stats snapshot into the committed artifact
    shape: per-handler p50/p99, loop lag, fan-out factors."""
    handlers = []
    for m in snap.get("methods", []):
        if not m.get("calls"):
            continue
        handlers.append({
            "method": m["method"],
            "calls": m["calls"],
            "errors": m["errors"],
            "p50_ms": round(m["handler_p50_s"] * 1e3, 3),
            "p99_ms": round(m["handler_p99_s"] * 1e3, 3),
            "queue_p99_ms": round(m["queue_wait_p99_s"] * 1e3, 3),
        })
    handlers = handlers[:top]
    loops = snap.get("loops", [])
    lag_p99 = max((lp["lag_p99_s"] for lp in loops), default=0.0)
    lag_p50 = max((lp["lag_p50_s"] for lp in loops), default=0.0)
    amp = snap.get("amplification", {})
    pubsub = amp.get("pubsub", [])
    kv = amp.get("kv", [])
    return {
        "handlers": handlers,
        "handlers_tracked": len(snap.get("methods", [])),
        "rpc_calls_total": sum(m["calls"]
                               for m in snap.get("methods", [])),
        "loop_lag_p50_ms": round(lag_p50 * 1e3, 3),
        "loop_lag_p99_ms": round(lag_p99 * 1e3, 3),
        "loop_stalls": sum(lp["stalls"] for lp in loops),
        "pubsub_fanout_max": max((c["fanout"] for c in pubsub),
                                 default=0),
        "kv_amplification_max": max((n["amplification"] for n in kv),
                                    default=0.0),
        "fanout": {"pubsub": pubsub, "kv": kv,
                   "pruned_subscribers": amp.get("pruned_total", 0)},
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    p.add_argument("--nodes", type=int, default=32,
                   help="logical nodes (virtual scheduling nodes; "
                   "the issue floor is 25)")
    p.add_argument("--tasks", type=int, default=400)
    p.add_argument("--actors", type=int, default=16)
    p.add_argument("--channels", type=int, default=4)
    p.add_argument("--publishes", type=int, default=200)
    p.add_argument("--kv-puts", type=int, default=200)
    p.add_argument("--top", type=int, default=12)
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu import api

    ray_tpu.init(num_cpus=8, num_tpus=0,
                 object_store_memory=1 << 30)
    for _ in range(args.nodes - 1):
        api._global_node.add_node({"CPU": 8.0})

    results = {"nodes": args.nodes}
    t_all = time.perf_counter()
    try:
        results["task_churn"] = _task_churn(args.tasks)
        results["actor_churn"] = _actor_churn(args.actors)
        results["pubsub_churn"] = _pubsub_churn(args.channels,
                                               args.publishes)
        results["kv_churn"] = _kv_churn(args.kv_puts)
        # Let the lag probes tick a little past the churn so the
        # histogram reflects loaded AND idle periods.
        time.sleep(1.0)

        from ray_tpu.util.state import _call

        snap = _call("rpc_stats", {"top": args.top})
        results.update(_summarize(snap, args.top))
        results["wall_s"] = round(time.perf_counter() - t_all, 2)
        results["run_date"] = time.strftime("%Y-%m-%d")
        print(json.dumps(results, indent=1))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=1)
                f.write("\n")
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
