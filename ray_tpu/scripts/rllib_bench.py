"""RLlib throughput benchmark.

Measures, per BASELINE.json's "PPO >= 50k env-steps/s/chip" target:
- raw vectorized env stepping (numpy dynamics only),
- env-runner sampling throughput (env stepping + batched policy
  forwards + rollout assembly),
- PPO end-to-end env-steps/s (sampling + learner updates),
on state obs (CartPole-v1), small pixel obs (PixelGridWorld-v0) and
the Atari-class pipeline (AtariLike-v0: 84x84x4 uint8 frame stacks).
``vs_target`` rides the Atari-class sampling number (r5; see PARITY.md
for this box's measured infra bounds); the gridworld numbers remain
for round-over-round comparability.
Run: python -m ray_tpu.scripts.rllib_bench [--json PATH]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def bench_env_stepping(env_name: str, num_envs: int = 256,
                       seconds: float = 3.0) -> float:
    from ray_tpu.rllib.env import make_vec

    env = make_vec(env_name, num_envs=num_envs, seed=0)
    env.reset()
    n = env.action_space.n
    rng = np.random.default_rng(0)
    actions = rng.integers(0, n, size=(64, num_envs)).astype(np.int32)
    env.step(actions[0])  # warm
    steps = 0
    start = time.perf_counter()
    while time.perf_counter() - start < seconds:
        for i in range(8):
            env.step(actions[i % 64])
        steps += 8 * num_envs
    return steps / (time.perf_counter() - start)


def bench_sampling(env_name: str, num_envs: int = 256,
                   rollout: int = 64, seconds: float = 5.0) -> float:
    from ray_tpu.rllib.env import make_vec
    from ray_tpu.rllib.env_runner import EnvRunner
    from ray_tpu.rllib.rl_module import RLModuleSpec

    probe = make_vec(env_name, num_envs=1)
    spec = RLModuleSpec(observation_space=probe.observation_space,
                        action_space=probe.action_space)
    runner = EnvRunner(env_name, num_envs=num_envs,
                       rollout_length=rollout, module_spec=spec, seed=0)
    runner.sample()  # compile + warm
    steps = 0
    start = time.perf_counter()
    while time.perf_counter() - start < seconds:
        batch = runner.sample()
        steps += batch["obs"].shape[0] * batch["obs"].shape[1]
    return steps / (time.perf_counter() - start)


def bench_ppo(env_name: str, seconds: float = 20.0) -> float:
    from ray_tpu.rllib import PPOConfig

    config = (PPOConfig()
              .environment(env_name)
              .env_runners(num_env_runners=2,
                           rollout_fragment_length=64)
              .training(train_batch_size=16384, num_epochs=2,
                        minibatch_size=4096))
    config.num_envs_per_env_runner = 128
    algo = config.build()
    try:
        algo.train()  # compile + warm
        steps = 0
        start = time.perf_counter()
        while time.perf_counter() - start < seconds:
            result = algo.train()
            steps += result["num_env_steps_sampled_this_iter"]
        return steps / (time.perf_counter() - start)
    finally:
        algo.stop()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    p.add_argument("--quick", action="store_true",
                   help="shorter measurement windows")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu) — the tunneled "
                        "axon TPU adds a WAN round-trip per forward that "
                        "swamps throughput numbers")
    args = p.parse_args()
    scale = 0.3 if args.quick else 1.0
    if args.platform:
        import os as _os

        _os.environ["JAX_PLATFORMS"] = args.platform
        import jax

        jax.config.update("jax_platforms", args.platform)

    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4, num_tpus=0)

    results = {}
    results["env_steps_per_s_cartpole"] = bench_env_stepping(
        "CartPole-v1", seconds=3 * scale)
    results["env_steps_per_s_pixel"] = bench_env_stepping(
        "PixelGridWorld-v0", num_envs=64, seconds=3 * scale)
    results["sampling_steps_per_s_cartpole"] = bench_sampling(
        "CartPole-v1", seconds=5 * scale)
    # 256 pixel envs: the per-step policy-forward dispatch amortizes
    # over the batch exactly as CartPole's does (same knob).
    results["sampling_steps_per_s_pixel"] = bench_sampling(
        "PixelGridWorld-v0", num_envs=256, seconds=5 * scale)
    # THE honest Atari-class numbers (r4 verdict #4): 84x84x4 uint8
    # frame stacks — real Atari obs volume (~28 KiB/obs, ~37x the toy
    # gridworld) through rendering + stack rolls + conv forwards.
    results["env_steps_per_s_atari84"] = bench_env_stepping(
        "AtariLike-v0", num_envs=64, seconds=3 * scale)
    results["sampling_steps_per_s_atari84"] = bench_sampling(
        "AtariLike-v0", num_envs=256, seconds=5 * scale)
    results["ppo_end_to_end_steps_per_s"] = bench_ppo(
        "CartPole-v1", seconds=20 * scale)
    results = {k: round(v, 1) for k, v in results.items()}
    results["target_ppo_steps_per_s"] = 50_000
    # The vs_target claim rides the Atari-CLASS pipeline, not the toy
    # pixel env (BASELINE.md: "PPO Atari >= 50k env-steps/s/chip").
    # On THIS dev box the number is bounded by infrastructure, not the
    # framework: every cluster process shares ONE CPU core (the conv
    # policy forward alone saturates it), and the tunneled TPU moves
    # ~15 MB/s (~500 obs/s of 28 KiB frames measured end to end), so
    # neither side can express a real chip's Atari throughput.
    results["vs_target"] = round(
        results["sampling_steps_per_s_atari84"] / 50_000, 3)
    results["vs_target_gridworld_pixel"] = round(
        results["sampling_steps_per_s_pixel"] / 50_000, 3)
    results["atari84_note"] = (
        "1-core box: conv policy forward is CPU-bound; tunneled TPU "
        "path is WAN-bandwidth-bound (~15 MB/s). See PARITY.md.")
    print(json.dumps(results, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
