"""Cross-node transfer bench: pull a ~1 GiB object over loopback DCN.

Reference analog: release/benchmarks object-store numbers (1 GiB
broadcast) — here the single-pull bandwidth plus the constant-memory
property of the streaming ingest (object_transfer._pull_from writes
chunks into a pre-reserved arena slot; RSS must not scale with object
size).

Writes BENCH_TRANSFER JSON: {loopback_pull_gibps, puller_rss_delta_mib}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def main(size_gib: float = 1.0, out: str | None = None):
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=2, num_tpus=0, resources={"hostA": 2},
                 object_store_memory=int(3.5 * (1 << 30)))
    from ray_tpu import api

    head_port = api._global_node.port
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--head-host", "127.0.0.1", "--head-port", str(head_port),
         "--num-cpus", "2", "--resources", '{"hostB": 2}',
         "--object-store-memory", str(3 << 30)],
        env=dict(os.environ),
    )
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("hostB"):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("node agent never joined")

        n = int(size_gib * (1 << 30) // 8)
        data = np.random.default_rng(0).random(n)
        ref = ray_tpu.put(data)

        @ray_tpu.remote(resources={"hostB": 1})
        class Puller:
            """Pinned pulling process: the second pull reuses the
            already-faulted arena pages, separating transfer bandwidth
            from this host's first-touch page-fault cost (on microVM
            infrastructure a cold fault is ~25us/page and dominates a
            cold pull; steady-state clusters recycle arena pages)."""

            def _anon_rss_kib(self):
                with open("/proc/self/status") as f:
                    for line in f:
                        if line.startswith("RssAnon"):
                            return int(line.split()[1])
                return 0

            def first_touch_floor(self, gib):
                """Infra floor: the rate at which THIS host supplies
                brand-new pages (plain anonymous memory, no framework
                code at all). On lazy-memory microVMs this is the hard
                ceiling for any COLD ingest — page supply, not the
                transfer plane, is the bottleneck; steady-state pulls
                recycle pages and don't pay it."""
                n = int(gib * (1 << 30))
                buf = bytearray(8)
                t0 = time.perf_counter()
                buf = bytearray(n)  # zero-filled: touches every page
                dt = time.perf_counter() - t0
                del buf
                return n / (1 << 30) / dt

            def pull_once(self, refs):
                r = refs[0]
                rss0 = self._anon_rss_kib()
                t0 = time.perf_counter()
                arr = ray_tpu.get(r, timeout=600)
                dt = time.perf_counter() - t0
                rss1 = self._anon_rss_kib()
                out = {
                    "seconds": dt,
                    "gib": arr.nbytes / (1 << 30),
                    # Anonymous (heap) RSS only: the shm destination
                    # pages are shared and intentionally object-sized.
                    "anon_rss_delta_mib": (rss1 - rss0) / 1024,
                    "checksum_head": float(arr[0]),
                }
                del arr
                return out

            def drop_local(self, refs):
                # Forget every local trace of the object so the next
                # get() re-pulls — but into recycled arena pages.
                from ray_tpu.core import native_store
                from ray_tpu import api

                cw = api._require_worker()
                cw.memory_store.delete(refs[0].id)
                arena = native_store.get_attached_arena()
                if arena is not None:
                    arena.delete(refs[0].id.binary())
                return True

        puller = Puller.remote()
        floor = ray_tpu.get(
            puller.first_touch_floor.remote(size_gib), timeout=900)
        cold = ray_tpu.get(puller.pull_once.remote([ref]), timeout=900)
        assert cold["checksum_head"] == float(data[0])
        ray_tpu.get(puller.drop_local.remote([ref]), timeout=60)
        steady = ray_tpu.get(puller.pull_once.remote([ref]), timeout=900)
        result = {
            "loopback_pull_gibps": round(
                steady["gib"] / steady["seconds"], 2),
            "loopback_pull_cold_gibps": round(
                cold["gib"] / cold["seconds"], 2),
            "first_touch_floor_gibps": round(floor, 2),
            "object_gib": round(steady["gib"], 2),
            "puller_anon_rss_delta_mib": round(
                steady["anon_rss_delta_mib"], 1),
        }
        print(json.dumps(result))
        if out:
            with open(out, "w") as f:
                json.dump(result, f, indent=1)
                f.write("\n")
        return result
    finally:
        agent.terminate()
        try:
            agent.wait(timeout=30)
        except Exception:
            agent.kill()
        ray_tpu.shutdown()


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--size-gib", type=float, default=1.0)
    p.add_argument("--out", default=None)
    a = p.parse_args()
    main(a.size_gib, a.out)
