"""Device-plane transfer bench: 1 GiB sharded jax.Array put/get.

Two lanes, both against the host-bounce baseline (BENCH_TRANSFER_r05:
every jax.Array put round-tripped host numpy + pickle + shm):

- shared-device get: producer and consumer share devices (same
  process) — the device plane returns the array BY REFERENCE. This is
  the ``train → serve`` colocated handoff; throughput is bounded only
  by bookkeeping, and host RSS delta is ~0.
- device→device pull: a separate process gets the same 1 GiB array via
  the per-shard protocol (resumable data-plane range reads +
  ``jax.device_put`` landings). Host staging is bounded by
  concurrency × shard size — never the whole array — reported as the
  staging high-water mark next to the raw MB/s.

Writes BENCH_TRANSFER JSON with both lanes plus the r05 baseline
numbers for the trajectory table.
"""

from __future__ import annotations

import json
import time


def _anon_rss_kib() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("RssAnon"):
                return int(line.split()[1])
    return 0


def main(size_gib: float = 1.0, out: str | None = None,
         baseline: str | None = None):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import ray_tpu

    n_dev = len(jax.devices())
    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=int(1.5 * (1 << 30)))
    try:
        rows = int(size_gib * (1 << 30) // (4 * 1024))
        rows -= rows % n_dev
        mesh = Mesh(np.array(jax.devices()).reshape(n_dev), ("data",))
        sharding = NamedSharding(mesh, P("data"))
        key = jax.random.PRNGKey(0)
        arr = jax.device_put(
            jax.random.uniform(key, (rows, 1024), jnp.float32), sharding)
        jax.block_until_ready(arr)
        gib = arr.nbytes / (1 << 30)

        # --- lane 1: shared-device (same-process) zero-copy get ---
        rss0 = _anon_rss_kib()
        t0 = time.perf_counter()
        ref = ray_tpu.put(arr)
        put_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = ray_tpu.get(ref)
        get_s = time.perf_counter() - t0
        rss1 = _anon_rss_kib()
        assert got is arr, "shared-device get must return by reference"

        # --- lane 2: device→device per-shard pull (separate process) ---
        @ray_tpu.remote
        class Puller:
            def pull(self, refs):
                import jax as _jax

                import ray_tpu as _rt
                from ray_tpu.core import device_objects

                rssa = _anon_rss_kib()
                t = time.perf_counter()
                value = _rt.get(refs[0], timeout=900)
                _jax.block_until_ready(value)
                dt = time.perf_counter() - t
                return {
                    "seconds": dt,
                    "gib": value.nbytes / (1 << 30),
                    "num_shards": len(value.sharding.device_set),
                    "staging_peak_mib":
                        device_objects.peak_staging_bytes() / (1 << 20),
                    # On CPU backends the assembled "device" buffers are
                    # host RAM, so subtract them to isolate the
                    # plane's own host cost.
                    "anon_rss_delta_mib":
                        (_anon_rss_kib() - rssa) / 1024
                        - value.nbytes / (1 << 20),
                    "checksum": float(value[0, 0]),
                }

            def drop_local(self, refs):
                """Forget the local device copy and cached envelope so
                the next get re-pulls — into recycled pages (the
                steady-state a serving fleet lives in; cold pulls are
                bounded by this infra's ~0.18 GiB/s page-supply floor,
                see BENCH_TRANSFER_r05 first_touch_floor_gibps)."""
                from ray_tpu import api
                from ray_tpu.core import device_objects

                cw = api._require_worker()
                device_objects.drop(refs[0].hex())
                cw.memory_store.delete(refs[0].id)
                return True

        puller = Puller.remote()
        cold = ray_tpu.get(puller.pull.remote([ref]), timeout=900)
        assert cold["checksum"] == float(arr[0, 0])
        ray_tpu.get(puller.drop_local.remote([ref]), timeout=60)
        pulled = ray_tpu.get(puller.pull.remote([ref]), timeout=900)
        assert pulled["checksum"] == float(arr[0, 0])

        base = {}
        if baseline:
            try:
                with open(baseline) as f:
                    base = json.load(f)
            except OSError:
                base = {}
        host_gibps = float(base.get("loopback_pull_gibps") or 0.0)
        shared_gibps = gib / max(get_s, 1e-9)
        pull_gibps = pulled["gib"] / pulled["seconds"]
        result = {
            "object_gib": round(gib, 2),
            "num_shards": n_dev,
            "device_put_seconds": round(put_s, 4),
            "device_get_shared_gibps": round(shared_gibps, 1),
            "device_get_shared_rss_delta_mib": round(
                (rss1 - rss0) / 1024, 1),
            "device_pull_gibps": round(pull_gibps, 2),
            "device_pull_cold_gibps": round(
                cold["gib"] / cold["seconds"], 2),
            "device_pull_staging_peak_mib": round(
                pulled["staging_peak_mib"], 1),
            "device_pull_anon_rss_delta_mib": round(
                pulled["anon_rss_delta_mib"], 1),
            "host_path_r05_gibps": host_gibps,
            "host_path_r05_cold_gibps": float(
                base.get("loopback_pull_cold_gibps") or 0.0),
            "vs_host_path_shared": (
                round(shared_gibps / host_gibps, 1) if host_gibps else None),
            "vs_host_path_pull": (
                round(pull_gibps / host_gibps, 2) if host_gibps else None),
        }
        print(json.dumps(result))
        if out:
            with open(out, "w") as f:
                json.dump(result, f, indent=1)
                f.write("\n")
        return result
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--size-gib", type=float, default=1.0)
    p.add_argument("--out", default=None)
    p.add_argument("--baseline", default=None)
    a = p.parse_args()
    main(a.size_gib, a.out, a.baseline)
