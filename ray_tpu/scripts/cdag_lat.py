"""Compiled-DAG latency probe: p50 of a 1-stage echo tick.

Run AFTER the cluster is warm — a cold worker pool's import CPU
poisons µs-scale latency (see microbenchmark.py's _warm)."""

import statistics
import time

import ray_tpu
from ray_tpu.dag import InputNode


def main():
    ray_tpu.init(num_cpus=4, num_tpus=0)

    @ray_tpu.remote
    def _warm():
        time.sleep(0.5)
        return 1

    ray_tpu.get([_warm.remote() for _ in range(4)], timeout=180)
    time.sleep(2)

    @ray_tpu.remote
    class _Echo:
        def fwd(self, x):
            return x

    echo = _Echo.options(num_cpus=0.01).remote()
    ray_tpu.get(echo.fwd.remote(0), timeout=60)
    cd = echo.fwd.bind(InputNode()).experimental_compile()
    cd.execute(0, timeout=60)
    lats = []
    for i in range(300):
        t0 = time.perf_counter()
        cd.execute(i, timeout=60)
        lats.append(time.perf_counter() - t0)
    cd.teardown()
    print(f"p50 {statistics.median(lats)*1e6:.0f}us")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
