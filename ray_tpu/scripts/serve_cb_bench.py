"""Serve continuous-batching load bench: N-thousand concurrent streams
through the HTTP proxy against an engine deployment.

The serving-quality numbers that matter for LLM token streaming at load
(reference: TTFT / inter-token latency under concurrency in the TPU
serving comparison literature): p50/p99 TTFT, inter-chunk latency,
aggregate chunks/s, and the shed rate (requests rejected honestly by
the engine's bounded admission queue or failed outright). Unlike the
``serve-stream`` lane (8 handle-level streams), this drives the FULL
ingress path — aiohttp client -> proxy SSE -> router -> replica engine
-> per-sequence stream lanes — at 1k+ concurrent streams.

Writes ``BENCH_SERVE_CB.json`` via ``--json``; importable (``run``).
Alongside the summary json, ``--json`` also snapshots the head's
metrics history store + alert state into ``<name>_HISTORY.json`` —
the BENCH artifact carries the run's trajectory (TTFT series, queue
depth, shed counters over time), not just the endpoint numbers.
"""

from __future__ import annotations

import asyncio
import statistics
import time
from typing import Dict, List

from ray_tpu.scripts.serve_stream_bench import _percentile


def _raise_nofile_limit(n: int) -> None:
    """1k+ concurrent sockets needs headroom over the common 1024
    soft cap; raise toward the hard limit, never above it."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = min(hard, max(soft, n))
    if want > soft:
        resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))


def run(num_streams: int = 1000, chunks_per_stream: int = 16,
        num_replicas: int = 2, max_batch_size: int = 128,
        http_port: int = 8463, init: bool = True) -> Dict[str, float]:
    import ray_tpu
    from ray_tpu import serve

    _raise_nofile_limit(num_streams * 2 + 256)
    if init and not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8, num_tpus=0)

    @serve.deployment(
        num_cpus=0.5, num_replicas=num_replicas,
        max_queued_stream_chunks=32,
        engine=serve.EngineConfig(
            max_batch_size=max_batch_size,
            max_queued=max(256, 2 * num_streams // num_replicas)),
    )
    class TokenGen:
        async def __call__(self, request):
            for i in range(chunks_per_stream):
                await asyncio.sleep(0.002)  # model decode iteration
                yield {"t": i}

    serve.run(TokenGen.bind(), name="cb_bench", http_port=http_port)

    url = f"http://127.0.0.1:{http_port}/"
    results = {"ttfts": [], "gaps": [], "chunks": 0, "shed": 0,
               "ok": 0}

    import aiohttp

    stream_timeout = aiohttp.ClientTimeout(total=600, sock_read=180)

    async def one_stream(session):
        t0 = time.perf_counter()
        last = None
        n = 0
        try:
            async with session.get(
                    url, headers={"Accept": "text/event-stream"},
                    timeout=stream_timeout) as resp:
                if resp.status != 200:
                    results["shed"] += 1
                    return
                async for line in resp.content:
                    if not line.startswith(b"data: {"):
                        continue
                    now = time.perf_counter()
                    if last is None:
                        results["ttfts"].append(now - t0)
                    else:
                        results["gaps"].append(now - last)
                    last = now
                    n += 1
            results["chunks"] += n
            results["ok"] += 1
        except Exception:
            results["shed"] += 1

    async def drive():
        conn = aiohttp.TCPConnector(limit=num_streams + 16)
        async with aiohttp.ClientSession(connector=conn) as session:
            # Warm the route + replicas before the measured burst.
            await one_stream(session)
            for key in ("ttfts", "gaps"):
                results[key].clear()
            results.update(chunks=0, shed=0, ok=0)
            t0 = time.perf_counter()
            await asyncio.gather(*[one_stream(session)
                                   for _ in range(num_streams)])
            return time.perf_counter() - t0

    elapsed = asyncio.run(drive())

    ttfts = sorted(results["ttfts"])
    gaps = sorted(results["gaps"])
    out = {
        "concurrent_streams": float(num_streams),
        "chunks_per_stream": float(chunks_per_stream),
        "replicas": float(num_replicas),
        "engine_max_batch_size": float(max_batch_size),
        "completed_streams": float(results["ok"]),
        "shed_rate": results["shed"] / max(1, num_streams),
        "ttft_p50_ms": (statistics.median(ttfts) * 1e3
                        if ttfts else 0.0),
        "ttft_p99_ms": _percentile(ttfts, 0.99) * 1e3,
        "inter_chunk_p50_ms": (statistics.median(gaps) * 1e3
                               if gaps else 0.0),
        "inter_chunk_p99_ms": _percentile(gaps, 0.99) * 1e3,
        "chunks_per_second": results["chunks"] / elapsed if elapsed
        else 0.0,
        "wall_s": elapsed,
    }
    for name, value in out.items():
        print(f"{name}: {value:,.3f}")
    serve.delete("cb_bench")
    return out


def main():
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--json", default=None)
    p.add_argument("--streams", type=int, default=1000)
    p.add_argument("--chunks", type=int, default=16)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--batch", type=int, default=128)
    args = p.parse_args()
    results = run(num_streams=args.streams,
                  chunks_per_stream=args.chunks,
                  num_replicas=args.replicas,
                  max_batch_size=args.batch)
    if args.json:
        import json

        with open(args.json, "w") as f:
            json.dump({k: round(v, 3) for k, v in results.items()}, f,
                      indent=1)
            f.write("\n")
        write_history_artifact(_history_path(args.json))


def _history_path(json_path: str) -> str:
    base = (json_path[:-5] if json_path.endswith(".json")
            else json_path)
    return f"{base}_HISTORY.json"


def write_history_artifact(path: str) -> bool:
    """Snapshot the head's metrics history + alert state next to the
    bench summary. Best-effort: a disabled health plane (or a cluster
    already torn down) prints a note instead of failing the bench."""
    try:
        from ray_tpu.util.state import _call

        hist = _call("metrics_history_snapshot", {"max_points": 360})
        alerts = _call("alerts")
        import json

        with open(path, "w") as f:
            json.dump({"history": hist, "alerts": alerts}, f, indent=1,
                      default=str)
            f.write("\n")
        print(f"history snapshot: {path} "
              f"({hist.get('series_count', 0)} series, "
              f"{hist.get('point_count', 0)} points, "
              f"{len(alerts.get('episodes', []))} alert episodes)")
        return True
    except Exception as e:  # noqa: BLE001 — artifact is decoration
        print(f"history snapshot unavailable: {e}")
        return False


if __name__ == "__main__":
    main()
