"""Dashboard: JSON state + Prometheus metrics over HTTP.

Reference: dashboard/head.py:81 (DashboardHead + modules serving REST
state APIs) and _private/metrics_agent.py (the Prometheus re-exporter).
The SPA frontend is out of scope; the API surface the reference's UI
consumes — cluster status, nodes, actors, tasks, jobs, metrics — is
served as JSON from an aiohttp actor.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

logger = logging.getLogger(__name__)

DASHBOARD_NAME = "RAY_TPU_DASHBOARD"


class DashboardActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._runner = None
        self._started = asyncio.get_event_loop().create_task(self._start())

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/api/cluster_status", self._cluster_status)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/task_summary", self._task_summary)
        app.router.add_get("/api/workers", self._workers)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/objects", self._objects)
        app.router.add_get("/api/autoscaler", self._autoscaler)
        app.router.add_get("/debug", self._debug)
        app.router.add_get("/api/debug", self._debug)
        app.router.add_get("/profile", self._profile)
        app.router.add_get("/api/profile", self._profile)
        app.router.add_get("/trace", self._trace)
        app.router.add_get("/api/trace", self._trace)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/metrics/history", self._metrics_history)
        app.router.add_get("/api/metrics/history", self._metrics_history)
        app.router.add_get("/alerts", self._alerts)
        app.router.add_get("/api/alerts", self._alerts)
        app.router.add_get("/rpc", self._rpc)
        app.router.add_get("/api/rpc", self._rpc)
        app.router.add_get("/healthz", self._healthz)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        logger.info("dashboard at http://%s:%d", self.host, self.port)

    async def ready(self) -> int:
        await self._started
        return self.port

    async def _json(self, producer):
        from aiohttp import web

        loop = asyncio.get_event_loop()
        try:
            # State calls block; keep them off this actor's loop.
            data = await loop.run_in_executor(None, producer)
            return web.json_response(data)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def _cluster_status(self, request):
        def produce():
            import ray_tpu

            return {
                "cluster_resources": ray_tpu.cluster_resources(),
                "available_resources": ray_tpu.available_resources(),
            }

        return await self._json(produce)

    async def _autoscaler(self, request):
        def produce():
            from ray_tpu.util.state import _call

            return _call("autoscaler_status")

        return await self._json(produce)

    async def _nodes(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.list_nodes)

    async def _actors(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.list_actors)

    async def _tasks(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.list_tasks)

    async def _task_summary(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.summarize_tasks)

    async def _workers(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.list_workers)

    async def _jobs(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.list_jobs)

    async def _objects(self, request):
        from ray_tpu.util import state as ust

        return await self._json(ust.list_objects)

    async def _debug(self, request):
        """Cluster debug dump (flight-recorder rings + live stacks +
        scheduler wait state) as JSON — the HTTP face of
        ``ray_tpu debug dump``."""
        def produce():
            from ray_tpu.util import debug as udebug
            from ray_tpu.util.state import _call

            include_stacks = request.query.get("stacks", "1") != "0"
            out = udebug.cluster_debug_dump(include_stacks=include_stacks)
            try:
                out["sched_state"] = _call("debug_sched_state")
            except Exception:
                pass
            return out

        return await self._json(produce)

    async def _profile(self, request):
        """On-demand cluster sampling profile — the HTTP face of
        ``ray_tpu profile``. Query params: ``kind`` (worker / task /
        actor / all), ``id``, ``duration`` (s, capped), ``hz``, and
        ``format=json|html`` (html renders the merged flamegraph)."""
        from aiohttp import web

        from ray_tpu.util import profiler
        from ray_tpu.util.state import _call

        kind = request.query.get("kind", "all")
        ident = request.query.get("id", "")
        fmt = request.query.get("format", "json")
        try:
            duration = min(float(request.query.get("duration", 2.0)),
                           60.0)
            hz = min(float(request.query.get("hz", 100.0)), 1000.0)
        except ValueError as e:
            # Malformed query numbers are the caller's error, not a 500.
            return web.json_response({"error": str(e)}, status=400)
        loop = asyncio.get_event_loop()
        try:
            reply = await loop.run_in_executor(
                None, lambda: _call("profile_capture_cluster", {
                    "kind": kind, "id": ident,
                    "duration_s": duration, "hz": hz}))
            if reply.get("error"):
                # Never render a capture error as an empty 0-sample
                # flamegraph — surface it regardless of format.
                return web.json_response({"error": reply["error"]},
                                         status=400)
            if fmt == "html":
                merged = profiler.merge_folded(
                    [e for e in reply.get("entries", [])
                     if not e.get("error")])
                html = profiler.flamegraph_html(
                    merged, title=f"ray_tpu profile {kind} {ident}")
                return web.Response(text=html,
                                    content_type="text/html")
            return web.json_response(reply)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def _trace(self, request):
        """On-demand cluster device trace — the HTTP face of
        ``ray_tpu profile --device``. Query params: ``kind`` (worker /
        task / actor / all), ``id``, ``duration`` (s, capped), and
        ``format=json|html`` (html renders the merged host+device
        timeline). JSON replies strip the raw gzipped trace bytes —
        fetch those via the CLI, which writes them per-source."""
        from aiohttp import web

        from ray_tpu.util import device_trace
        from ray_tpu.util.state import _call

        kind = request.query.get("kind", "all")
        ident = request.query.get("id", "")
        fmt = request.query.get("format", "json")
        try:
            duration = min(float(request.query.get("duration", 2.0)),
                           60.0)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        loop = asyncio.get_event_loop()
        try:
            reply = await loop.run_in_executor(
                None, lambda: _call("device_trace_capture_cluster", {
                    "kind": kind, "id": ident,
                    "duration_s": duration}))
            if reply.get("error"):
                return web.json_response({"error": reply["error"]},
                                         status=400)
            entries = reply.get("entries", [])
            if fmt == "html":
                html = device_trace.unified_timeline_html(
                    device_trace.merged_timeline_events(entries),
                    title=f"ray_tpu trace {kind} {ident}".strip())
                return web.Response(text=html,
                                    content_type="text/html")
            reply["entries"] = [device_trace.entry_json(e)
                                for e in entries]
            return web.json_response(reply)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)

    async def _metrics(self, request):
        from aiohttp import web

        from ray_tpu.util import metrics as um

        loop = asyncio.get_event_loop()
        try:
            text = await loop.run_in_executor(None, um.prometheus_text)
            return web.Response(text=text,
                                content_type="text/plain")
        except Exception as e:
            return web.Response(status=500, text=str(e))

    async def _metrics_history(self, request):
        """Head-side metrics time-series (cluster health plane). No
        query params: the series index. With ``name``: windowed points
        for that metric (``window`` seconds, optional ``agg`` /
        ``points`` cap / remaining params as tag filters)."""
        def produce():
            from ray_tpu.util.state import _call

            payload = {}
            q = request.query
            if q.get("name"):
                payload["name"] = q["name"]
                payload["window_s"] = float(q.get("window", 600.0))
                if q.get("agg"):
                    payload["agg"] = q["agg"]
                if q.get("points"):
                    payload["max_points"] = int(q["points"])
                tags = {k: v for k, v in q.items()
                        if k not in ("name", "window", "agg", "points")}
                if tags:
                    payload["tags"] = tags
            return _call("metrics_history", payload)

        return await self._json(produce)

    async def _alerts(self, request):
        """Firing alerts + recent fire/resolve episodes + rule set."""
        def produce():
            from ray_tpu.util.state import _call

            return _call("alerts")

        return await self._json(produce)

    async def _rpc(self, request):
        """Control-plane load observatory — the HTTP face of
        ``ray_tpu debug hotrpc``: per-handler server-side accounting,
        top talkers, event-loop lag, pubsub/KV amplification. Query
        params: ``top`` (table row cap), ``window`` (cluster loop-lag
        aggregation window, seconds)."""
        def produce():
            from ray_tpu.util.state import _call

            q = request.query
            return _call("rpc_stats", {
                "top": int(q.get("top", 20)),
                "window_s": float(q.get("window", 300.0)),
            })

        return await self._json(produce)

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="success")

    async def shutdown(self):
        if self._runner is not None:
            await self._runner.cleanup()


def start_dashboard(port: int = 8265):
    """Start (or get) the dashboard actor; returns the bound port."""
    import ray_tpu

    actor = (ray_tpu.remote(DashboardActor)
             .options(name=DASHBOARD_NAME, lifetime="detached",
                      get_if_exists=True, num_cpus=0.1)
             .remote("127.0.0.1", port))
    return ray_tpu.get(actor.ready.remote(), timeout=60)
