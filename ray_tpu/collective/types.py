"""Collective type declarations.

Reference surface: python/ray/util/collective/types.py (ReduceOp enum,
backend spec). The TPU build keeps the declarative group spec but replaces
the NCCL/Gloo backend pair with:

- ``host``: rendezvous-store exchange over the task/actor RPC plane (the
  gloo analog — DCN/host-side barriers, small tensors, bootstrap).
- ``xla``: same rendezvous for out-of-graph calls, but the *preferred*
  device path is in-graph XLA collectives (psum/all_gather/ppermute under
  shard_map over a named mesh — see ray_tpu/parallel/), which ride ICI and
  never touch the host. ``get_group_mesh`` bridges a collective group to
  that world.
"""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    HOST = "host"
    XLA = "xla"

    @classmethod
    def parse(cls, value: str) -> "Backend":
        v = str(value).lower()
        # Accept the reference's backend names so ported user code runs:
        # host-side groups stand in for gloo; xla groups for nccl.
        if v in ("host", "gloo", "cpu"):
            return cls.HOST
        if v in ("xla", "nccl", "tpu", "ici"):
            return cls.XLA
        raise ValueError(f"unknown collective backend: {value!r}")


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"
