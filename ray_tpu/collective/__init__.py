"""Collective communication for actor groups (reference:
python/ray/util/collective/)."""

from ray_tpu.collective.collective import (
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_group_mesh,
    get_rank,
    init_collective_group,
    is_group_initialized,
    list_declared_groups,
    local_group_names,
    recv,
    reducescatter,
    send,
)
from ray_tpu.collective.types import Backend, ReduceOp

__all__ = [
    "Backend",
    "ReduceOp",
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "broadcast",
    "create_collective_group",
    "destroy_collective_group",
    "get_collective_group_size",
    "get_group_mesh",
    "get_rank",
    "init_collective_group",
    "is_group_initialized",
    "list_declared_groups",
    "local_group_names",
    "recv",
    "reducescatter",
    "send",
]
