"""Collective communication API over actor groups.

Reference surface: python/ray/util/collective/collective.py —
init_collective_group(:120), create_collective_group(:151), allreduce(:258),
barrier(:298), broadcast(:373), allgather(:423), reducescatter(:472),
send(:531)/recv(:594). Same call signatures in spirit; the NCCL/Gloo
backends are replaced per ray_tpu/collective/types.py: the ``host`` backend
exchanges through the rendezvous store (gloo analog), and device-plane
traffic belongs in-graph (XLA collectives over a mesh — ``get_group_mesh``
hands callers the mesh for that).

Collective ordering contract (same as the reference): every rank must
issue the group's collectives in the same order; each op consumes one
sequence number on every rank.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.collective.types import Backend, ReduceOp
from ray_tpu.util.locks import make_lock as _make_lock

_DEFAULT_GROUP = "default"

_lock = _make_lock("collective.module._lock")
_groups: Dict[str, "GroupContext"] = {}
_store_handle = None


def _reset_state() -> None:
    """Forget cached store handle + group contexts. Called by
    ray_tpu.shutdown(); a later init() gets a fresh store actor."""
    global _store_handle
    with _lock:
        _groups.clear()
        _store_handle = None


def _api():
    import ray_tpu

    return ray_tpu


def _get_store():
    """Get-or-create the cluster-wide rendezvous store actor. Concurrent
    creators race on the name; the loser's registration dies, so retry via
    get_actor until a live store answers."""
    global _store_handle
    with _lock:
        if _store_handle is not None:
            return _store_handle
    # Slow path OUTSIDE the lock: creating + pinging the store actor
    # can take seconds (name races retry with sleeps), and holding
    # _lock across it would freeze every other collective call in this
    # process (lock-discipline: no blocking under a lock). Concurrent
    # creators converge on one actor via get_if_exists, so the losers
    # just re-cache the same handle.
    ray_tpu = _api()
    from ray_tpu.collective.store import (
        STORE_ACTOR_NAME,
        STORE_NAMESPACE,
        CollectiveStore,
    )

    last_err = None
    handle = None
    for _ in range(20):
        try:
            handle = (
                ray_tpu.remote(CollectiveStore)
                .options(name=STORE_ACTOR_NAME,
                         namespace=STORE_NAMESPACE,
                         lifetime="detached", get_if_exists=True,
                         num_cpus=0)
                .remote()
            )
            ray_tpu.get(handle.ping.remote(), timeout=10)
            break
        except Exception as e:  # lost the name race; retry lookup
            last_err = e
            handle = None
            import time

            time.sleep(0.1)
    if handle is None:
        raise RuntimeError(
            f"could not reach collective store actor: {last_err}")
    with _lock:
        if _store_handle is None:
            _store_handle = handle
        return _store_handle


class GroupContext:
    def __init__(self, group_name: str, rank: int, world_size: int,
                 backend: Backend, store, generation: int):
        self.group_name = group_name
        self.rank = rank
        self.world_size = world_size
        self.backend = backend
        self.store = store
        self.generation = generation
        self._seq = itertools.count()
        self._send_seq: Dict[int, "itertools.count"] = {}
        self._recv_seq: Dict[int, "itertools.count"] = {}
        self._op_lock = threading.Lock()

    def next_seq(self) -> int:
        with self._op_lock:
            return next(self._seq)

    def next_p2p_seq(self, table: Dict[int, Any], peer: int) -> int:
        with self._op_lock:
            if peer not in table:
                table[peer] = itertools.count()
            return next(table[peer])

    def exchange(self, payload, timeout: Optional[float] = None) -> list:
        seq = self.next_seq()
        ray_tpu = _api()
        return ray_tpu.get(self.store.exchange.remote(
            self.group_name, self.generation, seq, self.rank, payload,
            timeout))


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = _DEFAULT_GROUP) -> None:
    """Initialize this process's membership in a collective group.

    Call from every participating worker/actor with a distinct rank in
    ``[0, world_size)`` (reference: collective.py:120). Re-initializing is
    allowed after destroy_collective_group (new store generation); it
    replaces the stale local context."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    be = Backend.parse(backend)
    store = _get_store()
    ray_tpu = _api()
    info = ray_tpu.get(
        store.declare_group.remote(group_name, world_size, be.value))
    with _lock:
        existing = _groups.get(group_name)
        if existing is not None and \
                existing.generation == info["generation"]:
            raise RuntimeError(f"group {group_name!r} already initialized "
                               "in this process")
        _groups[group_name] = GroupContext(group_name, rank, world_size, be,
                                           store, info["generation"])


def create_collective_group(actors: Sequence[Any], world_size: int,
                            ranks: Sequence[int],
                            backend: str = "host",
                            group_name: str = _DEFAULT_GROUP) -> None:
    """Declare a group over actor handles from the driver; members pick up
    their rank lazily on first collective call (reference: collective.py:151
    declare + lazy init)."""
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("need exactly world_size actors and ranks")
    if sorted(int(r) for r in ranks) != list(range(world_size)):
        raise ValueError(
            f"ranks must be a permutation of 0..{world_size - 1}, "
            f"got {list(ranks)}")
    be = Backend.parse(backend)
    store = _get_store()
    members = {a._actor_id.hex(): int(r) for a, r in zip(actors, ranks)}
    ray_tpu = _api()
    ray_tpu.get(store.declare_group.remote(group_name, world_size, be.value,
                                           members))
    from ray_tpu.util import flight_recorder

    flight_recorder.record("collective", "group_created",
                           group=group_name, world_size=world_size,
                           backend=be.value)


def _get_ctx(group_name: str) -> GroupContext:
    with _lock:
        ctx = _groups.get(group_name)
    if ctx is not None:
        return ctx
    # Lazy init path for declaratively-created groups: look up this
    # actor's rank in the store's membership table.
    ray_tpu = _api()
    actor_hex = ray_tpu.get_runtime_context().get_actor_id()
    if actor_hex is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group() first")
    store = _get_store()
    info = ray_tpu.get(store.get_group.remote(group_name))
    if info is None or actor_hex not in info.get("members", {}):
        raise RuntimeError(
            f"collective group {group_name!r} is not declared for this actor")
    ctx = GroupContext(group_name, info["members"][actor_hex],
                       info["world_size"], Backend.parse(info["backend"]),
                       store, info["generation"])
    with _lock:
        held = _groups.get(group_name)
        if held is not None and held.generation >= ctx.generation:
            return held
        _groups[group_name] = ctx
        return ctx


def is_group_initialized(group_name: str = _DEFAULT_GROUP) -> bool:
    with _lock:
        return group_name in _groups


def local_group_names() -> list:
    """Group names this process has initialized (train gang heartbeats
    report these so the driver can destroy exactly the gang's groups on
    abort, waking peers blocked in ``exchange``)."""
    with _lock:
        return sorted(_groups)


def list_declared_groups() -> list:
    """Cluster-wide view: every group currently declared in the
    rendezvous store, callable from any process (gang-abort forensics —
    e.g. checking which groups survived a ``destroy_collective_group``
    sweep)."""
    ray_tpu = _api()
    store = _get_store()
    return ray_tpu.get(store.list_groups.remote())


def get_rank(group_name: str = _DEFAULT_GROUP) -> int:
    return _get_ctx(group_name).rank


def get_collective_group_size(group_name: str = _DEFAULT_GROUP) -> int:
    return _get_ctx(group_name).world_size


def destroy_collective_group(group_name: str = _DEFAULT_GROUP) -> None:
    """Tear down a group cluster-wide, waking any blocked ranks with an
    error. Callable from any process (e.g. the driver), not just members."""
    ray_tpu = _api()
    with _lock:
        _groups.pop(group_name, None)
    store = _get_store()
    ray_tpu.get(store.destroy_group.remote(group_name))
    from ray_tpu.util import flight_recorder

    flight_recorder.record("collective", "group_destroyed",
                           severity="warn", group=group_name)


# ---------------------------------------------------------------------------
# tensor plumbing
# ---------------------------------------------------------------------------


def _to_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, np.ndarray):
        return tensor
    # jax.Array / torch.Tensor / scalars all round-trip through numpy.
    return np.asarray(tensor)


def _like(result: np.ndarray, template):
    if isinstance(template, np.ndarray):
        return result
    mod = type(template).__module__
    if mod.startswith("jax"):
        import jax.numpy as jnp

        return jnp.asarray(result)
    if mod.startswith("torch"):
        import torch

        return torch.from_numpy(np.ascontiguousarray(result))
    if np.isscalar(template):
        return result.item() if result.ndim == 0 else result
    return result


def _reduce(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    stack = np.stack(arrays)
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return stack.prod(axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.MEAN:
        return stack.mean(axis=0)
    raise ValueError(f"unknown reduce op: {op}")


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------


def allreduce(tensor, group_name: str = _DEFAULT_GROUP,
              op: ReduceOp = ReduceOp.SUM, timeout: Optional[float] = None):
    """Reduce across all ranks; every rank gets the result
    (reference: collective.py:258)."""
    ctx = _get_ctx(group_name)
    parts = ctx.exchange(_to_numpy(tensor), timeout)
    return _like(_reduce(parts, ReduceOp(op)), tensor)


def allgather(tensor, group_name: str = _DEFAULT_GROUP,
              timeout: Optional[float] = None) -> list:
    """Every rank gets the rank-ordered list of all tensors
    (reference: collective.py:423)."""
    ctx = _get_ctx(group_name)
    parts = ctx.exchange(_to_numpy(tensor), timeout)
    return [_like(p, tensor) for p in parts]


def reducescatter(tensor, group_name: str = _DEFAULT_GROUP,
                  op: ReduceOp = ReduceOp.SUM,
                  timeout: Optional[float] = None):
    """Reduce then scatter: rank i gets the i-th equal chunk along axis 0
    (reference: collective.py:472)."""
    ctx = _get_ctx(group_name)
    arr = _to_numpy(tensor)
    if arr.shape[0] % ctx.world_size != 0:
        raise ValueError(
            f"reducescatter dim0={arr.shape[0]} not divisible by "
            f"world_size={ctx.world_size}")
    parts = ctx.exchange(arr, timeout)
    reduced = _reduce(parts, ReduceOp(op))
    chunk = reduced.shape[0] // ctx.world_size
    out = reduced[ctx.rank * chunk:(ctx.rank + 1) * chunk]
    return _like(out, tensor)


def broadcast(tensor, src_rank: int = 0, group_name: str = _DEFAULT_GROUP,
              timeout: Optional[float] = None):
    """All ranks get src_rank's tensor (reference: collective.py:373)."""
    ctx = _get_ctx(group_name)
    payload = _to_numpy(tensor) if ctx.rank == src_rank else None
    parts = ctx.exchange(payload, timeout)
    result = parts[src_rank]
    if result is None:
        raise RuntimeError(f"broadcast src rank {src_rank} sent no data")
    return _like(result, tensor)


def barrier(group_name: str = _DEFAULT_GROUP,
            timeout: Optional[float] = None) -> None:
    """Block until every rank arrives (reference: collective.py:298)."""
    _get_ctx(group_name).exchange(None, timeout)


def alltoall(tensors: Sequence[Any], group_name: str = _DEFAULT_GROUP,
             timeout: Optional[float] = None) -> list:
    """Rank i sends tensors[j] to rank j; returns what every rank sent to
    this one, rank-ordered. (No direct reference equivalent at the Python
    API level; NCCL groups expose it internally.)"""
    ctx = _get_ctx(group_name)
    if len(tensors) != ctx.world_size:
        raise ValueError("alltoall needs exactly world_size tensors")
    parts = ctx.exchange([_to_numpy(t) for t in tensors], timeout)
    return [_like(parts[j][ctx.rank], tensors[0])
            for j in range(ctx.world_size)]


def send(tensor, dst_rank: int, group_name: str = _DEFAULT_GROUP) -> None:
    """Point-to-point send (reference: collective.py:531). Ordered per
    (src, dst) pair."""
    ctx = _get_ctx(group_name)
    if dst_rank == ctx.rank:
        raise ValueError("cannot send to self")
    seq = ctx.next_p2p_seq(ctx._send_seq, dst_rank)
    ray_tpu = _api()
    ray_tpu.get(ctx.store.p2p_put.remote(
        group_name, ctx.generation, seq, ctx.rank, dst_rank,
        _to_numpy(tensor)))


def recv(tensor_template, src_rank: int, group_name: str = _DEFAULT_GROUP,
         timeout: Optional[float] = None):
    """Point-to-point receive; returns the tensor (the reference mutates
    in place — functional style here, collective.py:594)."""
    ctx = _get_ctx(group_name)
    if src_rank == ctx.rank:
        raise ValueError("cannot recv from self")
    seq = ctx.next_p2p_seq(ctx._recv_seq, src_rank)
    ray_tpu = _api()
    payload = ray_tpu.get(ctx.store.p2p_get.remote(
        group_name, ctx.generation, seq, src_rank, ctx.rank, timeout))
    return _like(payload, tensor_template)


# ---------------------------------------------------------------------------
# device-mesh bridge
# ---------------------------------------------------------------------------


def get_group_mesh(group_name: str = _DEFAULT_GROUP, axis_name: str = "ranks"):
    """Build a 1-D jax Mesh over this process's local devices for in-graph
    collectives scoped to the group. On a multi-host slice the worker group
    must have run jax.distributed.initialize (ray_tpu.train's JaxBackend
    does); then jax.devices() spans the slice and the mesh is global."""
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices())
    return Mesh(devices, (axis_name,))
