"""Rendezvous store actor for collective groups.

Reference: python/ray/util/collective/collective_group/nccl_util.py +
the named-actor rendezvous used by NCCLUniqueID exchange (reference
collective_group/rendezvous). Here the store is not just bootstrap — for
the ``host`` backend it is also the exchange plane: every collective op is
one ``exchange`` round (all ranks deposit, all ranks withdraw), which over
the in-process RPC transport costs two hops per rank. Device-plane
collectives should instead be in-graph XLA ops (ray_tpu/parallel/).

The store is an async actor, so all ranks of a group can block inside
``exchange`` concurrently on asyncio events.

Error semantics: a rank that times out inside a collective leaves the
group desynchronized (its peers may still be waiting on that seq) — same
contract as NCCL: after a timeout, destroy and recreate the group.
``destroy_group`` wakes all blocked waiters with an error. Every declare
after a destroy bumps the group's **generation**; ops carry the caller's
generation so stale GroupContexts from the old incarnation fail fast
instead of desynchronizing the new one.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

STORE_ACTOR_NAME = "_ray_tpu_collective_store"
STORE_NAMESPACE = "_ray_tpu_collective"

_DESTROYED = "__group_destroyed__"


class _Session:
    """One in-flight collective round: (group, gen, seq) -> deposits."""

    __slots__ = ("data", "done", "withdrawals", "destroyed")

    def __init__(self):
        self.data: Dict[int, Any] = {}
        self.done = asyncio.Event()
        self.withdrawals = 0
        self.destroyed = False


class CollectiveStore:
    """Group metadata + barrier/exchange sessions. One per cluster."""

    def __init__(self):
        self._groups: Dict[str, dict] = {}
        self._generations: Dict[str, int] = {}
        # (group, gen, seq) -> _Session
        self._sessions: Dict[tuple, _Session] = {}
        # (group, gen, seq, src, dst) -> payload / waiting event
        self._p2p: Dict[tuple, Any] = {}
        self._p2p_events: Dict[tuple, asyncio.Event] = {}

    async def declare_group(self, group_name: str, world_size: int,
                            backend: str,
                            members: Optional[Dict[str, int]] = None) -> dict:
        """Register (or validate) a group. ``members`` maps actor-id hex ->
        rank for declarative creation (create_collective_group)."""
        info = self._groups.get(group_name)
        if info is None:
            gen = self._generations.get(group_name, 0) + 1
            self._generations[group_name] = gen
            info = {"world_size": int(world_size), "backend": backend,
                    "members": dict(members or {}), "generation": gen}
            self._groups[group_name] = info
        else:
            if info["world_size"] != int(world_size):
                raise ValueError(
                    f"group {group_name!r} already declared with world_size="
                    f"{info['world_size']}, got {world_size}")
            if members:
                info["members"].update(members)
        return info

    async def get_group(self, group_name: str) -> Optional[dict]:
        return self._groups.get(group_name)

    async def list_groups(self) -> list:
        """Names of all declared groups (gang abort introspection)."""
        return sorted(self._groups)

    async def destroy_group(self, group_name: str) -> None:
        self._groups.pop(group_name, None)
        for key in [k for k in self._sessions if k[0] == group_name]:
            sess = self._sessions.pop(key)
            sess.destroyed = True
            sess.done.set()  # wake blocked waiters; they raise below
        # p2p: wake blocked receivers with a destroy marker; drop
        # undelivered payloads outright (their key's generation is dead, so
        # nothing can collide with a recreated group).
        for key in [k for k in self._p2p_events if k[0] == group_name]:
            if key not in self._p2p:  # a receiver is (or will be) waiting
                self._p2p[key] = _DESTROYED
                self._p2p_events[key].set()
        for key in [k for k in self._p2p if k[0] == group_name]:
            if self._p2p[key] is not _DESTROYED:
                self._p2p.pop(key)
                self._p2p_events.pop(key, None)

    def _check(self, group_name: str, generation: int) -> dict:
        info = self._groups.get(group_name)
        if info is None:
            raise ValueError(f"collective group {group_name!r} not declared")
        if info["generation"] != generation:
            raise RuntimeError(
                f"stale collective context for {group_name!r} (generation "
                f"{generation}, current {info['generation']}); re-init the "
                "group in this process")
        return info

    async def exchange(self, group_name: str, generation: int, seq: int,
                       rank: int, payload: Any,
                       timeout: Optional[float] = None) -> list:
        """All-to-all deposit/withdraw: blocks until every rank of the group
        has deposited for this ``seq``, then returns payloads rank-ordered."""
        info = self._check(group_name, generation)
        world = info["world_size"]
        key = (group_name, generation, seq)
        sess = self._sessions.get(key)
        if sess is None:
            sess = self._sessions[key] = _Session()
        if rank in sess.data:
            raise RuntimeError(
                f"rank {rank} deposited twice for {group_name}#{seq}")
        sess.data[rank] = payload
        if len(sess.data) == world:
            sess.done.set()
        else:
            try:
                await asyncio.wait_for(sess.done.wait(), timeout)
            except asyncio.TimeoutError:
                if not sess.done.is_set():
                    # Withdraw our deposit so peers can't complete the op
                    # with a payload whose sender saw a failure.
                    sess.data.pop(rank, None)
                    if not sess.data:
                        self._sessions.pop(key, None)
                    raise
        if sess.destroyed:
            raise RuntimeError(
                f"collective group {group_name!r} destroyed during op")
        out = [sess.data[r] for r in sorted(sess.data)]
        sess.withdrawals += 1
        if sess.withdrawals == world:
            self._sessions.pop(key, None)
        return out

    async def p2p_put(self, group_name: str, generation: int, seq: int,
                      src: int, dst: int, payload: Any) -> None:
        self._check(group_name, generation)
        key = (group_name, generation, seq, src, dst)
        self._p2p[key] = payload
        self._p2p_events.setdefault(key, asyncio.Event()).set()

    async def p2p_get(self, group_name: str, generation: int, seq: int,
                      src: int, dst: int,
                      timeout: Optional[float] = None) -> Any:
        self._check(group_name, generation)
        key = (group_name, generation, seq, src, dst)
        ev = self._p2p_events.setdefault(key, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            self._p2p_events.pop(key, None)
            raise
        self._p2p_events.pop(key, None)
        # Key may be gone if destroy_group raced the wakeup.
        payload = self._p2p.pop(key, _DESTROYED)
        if isinstance(payload, str) and payload == _DESTROYED:
            raise RuntimeError(
                f"collective group {group_name!r} destroyed during recv")
        return payload

    async def ping(self) -> str:
        return "ok"
