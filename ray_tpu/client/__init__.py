"""Thin-client driver — the Ray Client equivalent.

Reference: python/ray/util/client/__init__.py:217 (RayAPIStub) and
util/client/server/proxier.py — ``ray.init("ray://host:port")`` lets a
laptop/notebook drive a remote cluster through ONE outbound connection;
the cluster never dials the client back, so NAT'd/firewalled clients
work (a plain remote driver, by contrast, hosts an RPC server that
workers must reach to deliver results).

Usage:
    ray_tpu.init(address="rtpu://host:port")   # port = client server

The cluster side runs ``python -m ray_tpu.client.server`` (usually next
to the head; ``head_main --client-server-port`` starts one), which hosts
a REAL driver session and executes the api calls on the clients'
behalf. API calls are forwarded verbatim: tasks/actors (function and
class bytes shipped once, cached by digest), get/put/wait/kill/cancel,
and every head RPC the api layer issues (KV, placement groups, named
actors, cluster state) relays through the same connection.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu.core import rpc
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import Address

logger = logging.getLogger(__name__)


class ClientError(RuntimeError):
    pass


class _ClientRefCounter:
    """Client-side ref lifecycle: the proxy pins every ref it hands out;
    when the last client-side ObjectRef for an id dies, a release rides
    to the proxy (batched) so the cluster can free the object."""

    def __init__(self, worker: "ClientWorker"):
        self._worker = worker
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._to_release: List[str] = []

    def add_local_ref(self, ref: ObjectRef):
        with self._lock:
            h = ref.hex()
            self._counts[h] = self._counts.get(h, 0) + 1

    def remove_local_ref(self, ref: ObjectRef):
        flush = None
        with self._lock:
            h = ref.hex()
            n = self._counts.get(h, 0) - 1
            if n > 0:
                self._counts[h] = n
                return
            self._counts.pop(h, None)
            self._to_release.append(h)
            if len(self._to_release) >= 64:
                flush, self._to_release = self._to_release, []
        if flush:
            self._worker._release(flush)

    def flush_releases(self):
        with self._lock:
            flush, self._to_release = self._to_release, []
        if flush:
            self._worker._release(flush)

    def on_ref_serialized(self, ref: ObjectRef):
        pass  # the proxy owns and pins; no borrow protocol client-side

    def disable(self):
        with self._lock:
            self._counts.clear()
            self._to_release.clear()


class _ProxyHead:
    """Duck-typed HeadClient: api-layer head RPCs relay through the
    client connection (the proxy forwards to the real head)."""

    def __init__(self, worker: "ClientWorker"):
        self._worker = worker

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None):
        reply = await self._worker._conn.call(
            "c_head", {"m": method, "p": payload}, timeout=timeout)
        if reply.get("err") is not None:
            raise cloudpickle.loads(reply["err"])
        return reply["r"]


class ClientWorker:
    """Implements the CoreWorker surface the api layer consumes, by
    forwarding every operation to the cluster-side client server."""

    def __init__(self, host: str, port: int, namespace: str = ""):
        self.loop_thread = rpc.EventLoopThread(name="rtpu-client")
        self.namespace = namespace
        self.worker_id = WorkerID.from_random()
        self.node_id_hex: Optional[str] = None
        self.no_node_store = True
        self._exported: Dict[str, str] = {}  # digest -> proxy key
        self._closed = False

        async def boot():
            conn = await rpc.connect(host, port, {}, name="rtpu-client")
            self._conn = conn
            return await conn.call("c_handshake", {
                "namespace": namespace,
                "worker_id": self.worker_id.hex(),
            })

        try:
            reply = self.loop_thread.run(boot(), timeout=30)
        except BaseException:
            self.loop_thread.stop()
            raise
        self.job_id = JobID.from_hex(reply["job_id"])
        self._root_task_id = TaskID.for_normal_task(self.job_id)
        self._proxy_address = tuple(reply["address"])
        self.reference_counter = _ClientRefCounter(self)
        self.head = _ProxyHead(self)
        self._attached_loop_thread = self.loop_thread

    # -- plumbing ------------------------------------------------------

    def _call(self, method: str, payload: dict,
              timeout: Optional[float] = None):
        reply = self.loop_thread.run(
            self._conn.call(method, payload, timeout=timeout))
        if reply.get("err") is not None:
            raise cloudpickle.loads(reply["err"])
        return reply

    def _release(self, hex_ids: List[str]):
        if self._closed:
            return
        try:
            self.loop_thread.submit(
                self._conn.notify("c_release", {"ids": hex_ids}))
        except Exception:
            pass  # connection gone; the proxy reaps on disconnect

    def _mk_ref(self, hex_id: str) -> ObjectRef:
        owner = Address(self._proxy_address[0], self._proxy_address[1],
                        self._proxy_address[2])
        return ObjectRef(ObjectID.from_hex(hex_id), owner)

    # -- function/actor export ----------------------------------------

    def export_function(self, fn) -> str:
        blob = cloudpickle.dumps(fn, protocol=5)
        digest = hashlib.sha1(blob).hexdigest()
        key = self._exported.get(digest)
        if key is None:
            key = self._call("c_export", {"blob": blob})["key"]
            self._exported[digest] = key
        return key

    # -- task/actor submission ----------------------------------------

    def serialize_args(self, args: tuple, kwargs: dict) -> bytes:
        # ObjectRefs/ActorHandles pickle by id + proxy owner address and
        # rebuild as REAL refs inside the proxy's driver session.
        return cloudpickle.dumps((args, kwargs), protocol=5)

    def submit_task(self, function_key: str, args_blob: bytes, *,
                    name: str, num_returns: int,
                    resources: Dict[str, float], max_retries: int,
                    retry_exceptions: bool, scheduling_strategy,
                    runtime_env=None,
                    stream_window: int = 0) -> List[ObjectRef]:
        # stream_window accepted for API parity; the proxy rejects
        # streaming submissions (num_returns == -1) server-side.
        reply = self._call("c_task", {
            "key": function_key, "args": args_blob,
            "opts": cloudpickle.dumps({
                "name": name, "num_returns": num_returns,
                "resources": resources, "max_retries": max_retries,
                "retry_exceptions": retry_exceptions,
                "scheduling_strategy": scheduling_strategy,
                "runtime_env": runtime_env,
            }),
        })
        return [self._mk_ref(h) for h in reply["refs"]]

    def create_actor(self, class_key: str, args_blob: bytes, *,
                     name: str, actor_name: str, namespace: str,
                     resources: Dict[str, float], max_restarts: int,
                     max_task_retries: int, max_concurrency: int,
                     is_async: bool, scheduling_strategy,
                     runtime_env=None, detached: bool = False) -> ActorID:
        reply = self._call("c_actor", {
            "key": class_key, "args": args_blob,
            "opts": cloudpickle.dumps({
                "name": name, "actor_name": actor_name,
                "namespace": namespace or self.namespace,
                "resources": resources, "max_restarts": max_restarts,
                "max_task_retries": max_task_retries,
                "max_concurrency": max_concurrency,
                "is_async": is_async,
                "scheduling_strategy": scheduling_strategy,
                "runtime_env": runtime_env, "detached": detached,
            }),
        })
        return ActorID.from_hex(reply["actor_id"])

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args_blob: bytes, *, num_returns: int,
                          name: str = "",
                          stream_window: int = 0) -> List[ObjectRef]:
        reply = self._call("c_actor_call", {
            "actor_id": actor_id.hex(), "method": method_name,
            "args": args_blob, "num_returns": num_returns,
            "name": name,
        })
        return [self._mk_ref(h) for h in reply["refs"]]

    # -- data plane ----------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        reply = self._call(
            "c_put", {"blob": cloudpickle.dumps(value, protocol=5)})
        return self._mk_ref(reply["ref"])

    def get(self, refs: List[ObjectRef],
            timeout: Optional[float] = None,
            donate: bool = False) -> List[Any]:
        # ``donate`` is a device-plane transfer optimization; values
        # reach a client as pickled host data, so there is no holder-
        # side buffer to release — accepted for API parity, ignored.
        reply = self._call(
            "c_get", {"ids": [r.hex() for r in refs],
                      "timeout": timeout},
            timeout=None if timeout is None else timeout + 30)
        return cloudpickle.loads(reply["values"])

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool):
        reply = self._call("c_wait", {
            "ids": [r.hex() for r in refs], "num_returns": num_returns,
            "timeout": timeout, "fetch_local": fetch_local,
        }, timeout=None if timeout is None else timeout + 30)
        ready_set = set(reply["ready"])
        ready = [r for r in refs if r.hex() in ready_set]
        not_ready = [r for r in refs if r.hex() not in ready_set]
        return ready, not_ready

    # -- control -------------------------------------------------------

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._call("c_kill", {"actor_id": actor_id.hex(),
                              "no_restart": no_restart})

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        self._call("c_cancel", {"id": ref.hex(), "force": force})

    def current_task_id(self) -> TaskID:
        return self._root_task_id

    def _on_actor_state_threadsafe(self, data: dict):
        """No-op: the api layer pushes named-actor table rows here for
        the real CoreWorker's call-routing cache; the thin client
        routes every call through the proxy instead."""

    def export_actor_class(self, cls) -> str:
        return self.export_function(cls)

    async def stop(self):
        # Ship every pending release BEFORE closing (the proxy also
        # reaps on disconnect; this is the graceful path).
        with self.reference_counter._lock:
            pending, self.reference_counter._to_release = (
                self.reference_counter._to_release, [])
        if pending:
            try:
                await self._conn.notify("c_release", {"ids": pending})
            except Exception:
                pass
        self._closed = True
        self.reference_counter.disable()
        try:
            await self._conn.close()
        except Exception:
            pass


def connect(address: str, namespace: str = "") -> ClientWorker:
    """``address`` is "host:port" of a ray_tpu.client.server."""
    from ray_tpu.core import object_ref as object_ref_mod

    host, port_s = address.rsplit(":", 1)
    worker = ClientWorker(host, int(port_s), namespace=namespace)
    object_ref_mod.set_core_worker(worker)
    return worker
