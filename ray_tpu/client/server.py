"""Cluster-side client server (reference: util/client/server/proxier.py
— the process that terminates ``ray://`` connections and executes api
calls on the clients' behalf).

Hosts ONE real driver session (``ray_tpu.init(address=head)``) and a
dedicated RPC server for thin clients. Blocking driver calls run on an
executor pool so one slow ``get`` never stalls other clients' requests.

Design note vs the reference: Ray's proxier forks a fresh driver per
client for job isolation; here all clients share the server's driver
session (single job id) — a deliberate simplification recorded in
PARITY.md. The NAT property (client only dials out) is identical.

Run next to the head:
    python -m ray_tpu.core.head_main --client-server-port 10001
or standalone:
    python -m ray_tpu.client.server --head 127.0.0.1:6379 --port 10001
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Dict, Optional

import cloudpickle

from ray_tpu.core import rpc

logger = logging.getLogger(__name__)


class ClientServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        self._host = host
        self._port = port
        self._fns: Dict[str, str] = {}      # digest -> exported key
        self._refs: Dict[str, object] = {}  # hex -> pinned ObjectRef
        self._counter = itertools.count()
        self.loop_thread = rpc.EventLoopThread(name="rtpu-client-srv")
        self.server: Optional[rpc.Server] = None
        self.port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> int:
        async def boot():
            self.server = rpc.Server(self._handlers(),
                                     name="client-server")
            self.server.on_connect = self._on_connect
            return await self.server.start(self._host, self._port)

        self.port = self.loop_thread.run(boot())
        logger.info("client server listening on %s:%d",
                    self._host, self.port)
        return self.port

    def stop(self):
        try:
            self.loop_thread.run(self.server.stop(), timeout=5)
        except Exception:
            pass
        self.loop_thread.stop()

    def _on_connect(self, conn):
        """Chain a disconnect reaper: refs pinned for a vanished client
        must not pin the shared driver session's objects forever."""
        prev = conn.on_close

        def closed(c):
            if prev is not None:
                prev(c)
            mine = c.state.pop("client_refs", set())
            if not mine:
                return
            still_held = set()
            for other in list(self.server.connections):
                still_held |= other.state.get("client_refs", set())
            for h in mine - still_held:
                self._refs.pop(h, None)

        conn.on_close = closed

    # -- helpers -------------------------------------------------------

    def _handlers(self) -> dict:
        return {
            "c_handshake": self.h_handshake,
            "c_export": self.h_export,
            "c_task": self.h_task,
            "c_actor": self.h_actor,
            "c_actor_call": self.h_actor_call,
            "c_put": self.h_put,
            "c_get": self.h_get,
            "c_wait": self.h_wait,
            "c_kill": self.h_kill,
            "c_cancel": self.h_cancel,
            "c_release": self.h_release,
            "c_head": self.h_head,
        }

    @staticmethod
    async def _blocking(fn, *args):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, fn, *args)

    @staticmethod
    def _guard(fn):
        """Run ``fn`` and pack the result; exceptions travel to the
        client serialized (it re-raises the original)."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — relayed, not swallowed
            try:
                blob = cloudpickle.dumps(e)
            except Exception:
                blob = cloudpickle.dumps(
                    RuntimeError(f"{type(e).__name__}: {e}"))
            return {"err": blob}

    def _pin(self, refs, conn) -> list:
        out = []
        mine = conn.state.setdefault("client_refs", set())
        for ref in refs:
            h = ref.hex()
            self._refs[h] = ref
            mine.add(h)
            out.append(h)
        return out

    def _resolve(self, hex_id: str):
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        ref = self._refs.get(hex_id)
        if ref is not None:
            return ref
        # A ref the client rebuilt from a value payload: this driver
        # owns it (proxy-minted ids), so a bare rebuild resolves.
        return ObjectRef(ObjectID.from_hex(hex_id))

    # -- handlers ------------------------------------------------------

    async def h_handshake(self, conn, payload):
        from ray_tpu import api

        cw = api._require_worker()
        return {"job_id": cw.job_id.hex(),
                "address": [cw.address.host, cw.address.port,
                            cw.address.worker_id_hex]}

    async def h_export(self, conn, payload):
        import hashlib

        blob = payload["blob"]
        digest = hashlib.sha1(blob).hexdigest()
        key = self._fns.get(digest)
        if key is None:
            def run():
                from ray_tpu import api

                fn = cloudpickle.loads(blob)
                return api._require_worker().export_function(fn)

            key = await self._blocking(run)
            self._fns[digest] = key
        return {"key": key}

    async def h_task(self, conn, payload):
        def run():
            return self._guard(lambda: self._do_task(payload, conn))

        return await self._blocking(run)

    def _do_task(self, payload, conn):
        from ray_tpu import api

        cw = api._require_worker()
        args, kwargs = cloudpickle.loads(payload["args"])
        opts = cloudpickle.loads(payload["opts"])
        if opts["num_returns"] == -1:
            raise NotImplementedError(
                "streaming tasks (num_returns='streaming') are not "
                "supported through the thin client yet; use a remote "
                "driver (address='host:port') for streaming generators")
        task_args = cw.serialize_args(args, kwargs)
        refs = cw.submit_task(
            payload["key"], task_args,
            name=opts["name"], num_returns=opts["num_returns"],
            resources=opts["resources"],
            max_retries=opts["max_retries"],
            retry_exceptions=opts["retry_exceptions"],
            scheduling_strategy=opts["scheduling_strategy"],
            runtime_env=opts["runtime_env"],
        )
        return {"refs": self._pin(refs, conn)}

    async def h_actor(self, conn, payload):
        def run():
            return self._guard(lambda: self._do_actor(payload))

        return await self._blocking(run)

    def _do_actor(self, payload):
        from ray_tpu import api

        cw = api._require_worker()
        args, kwargs = cloudpickle.loads(payload["args"])
        opts = cloudpickle.loads(payload["opts"])
        task_args = cw.serialize_args(args, kwargs)
        actor_id = cw.create_actor(
            payload["key"], task_args,
            name=opts["name"], actor_name=opts["actor_name"],
            namespace=opts["namespace"], resources=opts["resources"],
            max_restarts=opts["max_restarts"],
            max_task_retries=opts["max_task_retries"],
            max_concurrency=opts["max_concurrency"],
            is_async=opts["is_async"],
            scheduling_strategy=opts["scheduling_strategy"],
            runtime_env=opts["runtime_env"],
            detached=opts["detached"],
        )
        return {"actor_id": actor_id.hex()}

    async def h_actor_call(self, conn, payload):
        def run():
            return self._guard(
                lambda: self._do_actor_call(payload, conn))

        return await self._blocking(run)

    def _do_actor_call(self, payload, conn):
        from ray_tpu import api
        from ray_tpu.core.ids import ActorID

        cw = api._require_worker()
        if payload["num_returns"] == -1:
            raise NotImplementedError(
                "streaming actor calls (num_returns='streaming') are "
                "not supported through the thin client yet; use a "
                "remote driver (address='host:port') for streaming "
                "generators")
        args, kwargs = cloudpickle.loads(payload["args"])
        task_args = cw.serialize_args(args, kwargs)
        refs = cw.submit_actor_task(
            ActorID.from_hex(payload["actor_id"]), payload["method"],
            task_args, num_returns=payload["num_returns"],
            name=payload.get("name", ""),
        )
        return {"refs": self._pin(refs, conn)}

    async def h_put(self, conn, payload):
        def run():
            def inner():
                from ray_tpu import api

                value = cloudpickle.loads(payload["blob"])
                ref = api._require_worker().put(value)
                return {"ref": self._pin([ref], conn)[0]}
            return self._guard(inner)

        return await self._blocking(run)

    async def h_get(self, conn, payload):
        def run():
            def inner():
                from ray_tpu import api

                refs = [self._resolve(h) for h in payload["ids"]]
                values = api._require_worker().get(
                    refs, payload.get("timeout"))
                return {"values": cloudpickle.dumps(values,
                                                    protocol=5)}
            return self._guard(inner)

        return await self._blocking(run)

    async def h_wait(self, conn, payload):
        def run():
            def inner():
                from ray_tpu import api

                refs = [self._resolve(h) for h in payload["ids"]]
                ready, _ = api._require_worker().wait(
                    refs, payload["num_returns"], payload["timeout"],
                    payload["fetch_local"])
                return {"ready": [r.hex() for r in ready]}
            return self._guard(inner)

        return await self._blocking(run)

    async def h_kill(self, conn, payload):
        def run():
            def inner():
                from ray_tpu import api
                from ray_tpu.core.ids import ActorID

                api._require_worker().kill_actor(
                    ActorID.from_hex(payload["actor_id"]),
                    payload["no_restart"])
                return {"ok": True}
            return self._guard(inner)

        return await self._blocking(run)

    async def h_cancel(self, conn, payload):
        def run():
            def inner():
                from ray_tpu import api

                api._require_worker().cancel_task(
                    self._resolve(payload["id"]), payload["force"])
                return {"ok": True}
            return self._guard(inner)

        return await self._blocking(run)

    async def h_release(self, conn, payload):
        for hex_id in payload.get("ids", []):
            self._refs.pop(hex_id, None)

    async def h_head(self, conn, payload):
        def run():
            def inner():
                from ray_tpu import api

                cw = api._require_worker()
                return {"r": cw.loop_thread.run(cw.head.call(
                    payload["m"], payload["p"]))}
            return self._guard(inner)

        return await self._blocking(run)


def main():
    import argparse

    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--head", required=True, help="head host:port")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=10001)
    args = p.parse_args()

    import ray_tpu

    ray_tpu.init(address=args.head)
    srv = ClientServer(args.host, args.port)
    port = srv.start()
    print(f"ray_tpu client server on {args.host}:{port}", flush=True)
    import threading

    threading.Event().wait()


if __name__ == "__main__":
    main()
