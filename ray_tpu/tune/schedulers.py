"""Trial schedulers: early stopping + population-based training.

Reference: python/ray/tune/schedulers/ — async_hyperband.py (ASHA),
median_stopping_rule.py, hyperband.py, pbt.py. Decisions are made on
every reported result: CONTINUE, STOP, or (PBT) an exploit/explore
directive carrying a source checkpoint + mutated config.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode
        self._sign = 1.0 if mode == "max" else -1.0

    def score(self, result: dict) -> float:
        return self._sign * float(result[self.metric])

    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[dict]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py:AsyncHyperBandScheduler).

    Brackets of rungs at milestones grace_period * reduction_factor^k; a
    trial reaching a rung continues only if its score is in the top
    1/reduction_factor of scores recorded at that rung.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self.rungs: Dict[int, List[float]] = {}
        # trial_id -> highest rung already evaluated (so float-valued or
        # skipping time_attrs still hit each rung exactly once; reference
        # ASHA also compares t >= milestone, not equality).
        self._trial_rung: Dict[str, int] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        done_rung = self._trial_rung.get(trial.trial_id, -1)
        for i, m in enumerate(self.milestones):
            if i <= done_rung or t < m:
                continue
            self._trial_rung[trial.trial_id] = i
            scores = self.rungs.setdefault(m, [])
            s = self.score(result)
            scores.append(s)
            k = max(1, int(math.ceil(len(scores) / self.rf)))
            top = sorted(scores, reverse=True)[:k]
            if s < top[-1]:
                return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is below the median of the running
    averages of completed/running trials at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(self.score(result))
        if t <= self.grace_period:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._avgs.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(hist)
        return STOP if best < median else CONTINUE


class ExploitDirective:
    """PBT decision: restore from `source_trial_id`'s checkpoint and adopt
    `new_config`."""

    def __init__(self, source_trial_id: str, new_config: dict):
        self.source_trial_id = source_trial_id
        self.new_config = new_config


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py:PopulationBasedTraining).

    Every perturbation_interval, a bottom-quantile trial exploits a
    top-quantile trial's checkpoint and perturbs hyperparameters in
    hyperparam_mutations (×1.2 / ×0.8 for numeric, resample for lists).
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self._latest: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    def _perturb(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if isinstance(spec, list):
                new[key] = self.rng.choice(spec)
            elif callable(spec):
                new[key] = spec()
            else:
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                new[key] = new[key] * factor
        return new

    def on_result(self, trial, result: dict):
        t = result.get(self.time_attr, 0)
        self._latest[trial.trial_id] = self.score(result)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        if len(self._latest) < 2:
            return CONTINUE
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and top:
            source = self.rng.choice(top)
            if source != trial.trial_id:
                return ExploitDirective(source, self._perturb(trial.config))
        return CONTINUE

    def on_trial_complete(self, trial, result):
        self._latest.pop(trial.trial_id, None)
