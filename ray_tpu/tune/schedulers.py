"""Trial schedulers: early stopping + population-based training.

Reference: python/ray/tune/schedulers/ — async_hyperband.py (ASHA),
median_stopping_rule.py, hyperband.py, pbt.py. Decisions are made on
every reported result: CONTINUE, STOP, or (PBT) an exploit/explore
directive carrying a source checkpoint + mutated config.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"
RESUME = "RESUME"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode
        self._sign = 1.0 if mode == "max" else -1.0

    def score(self, result: dict) -> float:
        return self._sign * float(result[self.metric])

    def on_trial_add(self, trial):
        """Called when the controller creates a trial (before it runs)."""

    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def paused_actions(self, paused_trials) -> Dict[str, str]:
        """Decide the fate of paused trials: trial_id -> RESUME | STOP.

        Called by the controller each loop iteration while any trial is
        paused. Trials absent from the returned dict stay paused.
        """
        return {}

    def on_search_exhausted(self):
        """The search algorithm will produce no further trials."""

    def on_trial_complete(self, trial, result: Optional[dict]):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py:AsyncHyperBandScheduler).

    Brackets of rungs at milestones grace_period * reduction_factor^k; a
    trial reaching a rung continues only if its score is in the top
    1/reduction_factor of scores recorded at that rung.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self.rungs: Dict[int, List[float]] = {}
        # trial_id -> highest rung already evaluated (so float-valued or
        # skipping time_attrs still hit each rung exactly once; reference
        # ASHA also compares t >= milestone, not equality).
        self._trial_rung: Dict[str, int] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        done_rung = self._trial_rung.get(trial.trial_id, -1)
        for i, m in enumerate(self.milestones):
            if i <= done_rung or t < m:
                continue
            self._trial_rung[trial.trial_id] = i
            scores = self.rungs.setdefault(m, [])
            s = self.score(result)
            scores.append(s)
            k = max(1, int(math.ceil(len(scores) / self.rf)))
            top = sorted(scores, reverse=True)[:k]
            if s < top[-1]:
                return STOP
        return CONTINUE


class _Bracket:
    """One HyperBand bracket: n trials, initial budget r, halved by eta
    at each rung until the milestone reaches max_t."""

    def __init__(self, s: int, s_max: int, max_t: int, eta: int):
        self.s = s
        self.eta = eta
        self.max_t = max_t
        self.capacity = int(math.ceil((s_max + 1) * eta ** s / (s + 1)))
        self.r0 = max(1, int(round(max_t * eta ** -s)))
        self.rung = 0
        self.milestone = min(max_t, self.r0)
        self.added = 0                # total trials ever assigned
        self.live: set = set()        # trial_ids not yet cut/finished
        self.pending_scores: Dict[str, float] = {}  # paused at milestone

    def full(self) -> bool:
        return self.added >= self.capacity

    def add(self, trial_id: str):
        self.added += 1
        self.live.add(trial_id)

    def remove(self, trial_id: str):
        self.live.discard(trial_id)
        self.pending_scores.pop(trial_id, None)

    def record_pause(self, trial_id: str, score: float):
        self.pending_scores[trial_id] = score

    def ready_to_halve(self, no_more_trials: bool) -> bool:
        # A bracket only halves once its cohort is complete — either
        # filled to capacity or the search can add no more — so that
        # incrementally-arriving trials (Searcher-driven) are compared
        # against their full rung cohort, not promoted in cohorts of one.
        if not (self.full() or no_more_trials):
            return False
        return (bool(self.live)
                and set(self.pending_scores) >= self.live)

    def halve(self) -> Dict[str, str]:
        """All live trials paused at the milestone: keep the top
        len/eta, stop the rest, advance the rung."""
        ranked = sorted(self.live, key=lambda t: self.pending_scores[t],
                        reverse=True)
        keep = max(1, len(ranked) // self.eta)
        survivors, losers = ranked[:keep], ranked[keep:]
        actions = {t: RESUME for t in survivors}
        actions.update({t: STOP for t in losers})
        for t in losers:
            self.remove(t)
        self.pending_scores.clear()
        self.rung += 1
        self.milestone = min(self.max_t,
                             self.r0 * self.eta ** self.rung)
        return actions


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (Li et al., JMLR 2018).

    Reference: python/ray/tune/schedulers/hyperband.py:HyperBandScheduler.
    Trials fill brackets s = s_max .. 0 in order (a "band"); each bracket
    runs its cohort to a rung milestone, pauses every trial there, keeps
    the top 1/eta by the metric and stops the rest, then resumes the
    survivors toward the next milestone (r0 * eta^k, capped at max_t).
    Unlike ASHA the halving is synchronous — a bracket waits for all of
    its live trials before promoting, which is exactly the reference
    semantics and requires the controller's pause/resume support.
    Pausing checkpoints the trial; class trainables resume in place.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: int = 3):
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # Integer log (math.log(243, 3) == 4.999... would truncate).
        s_max, t = 0, reduction_factor
        while t <= max_t:
            s_max += 1
            t *= reduction_factor
        self.s_max = s_max
        self._brackets: List[_Bracket] = []
        self._by_trial: Dict[str, _Bracket] = {}
        self._next_s = self.s_max
        self._no_more_trials = False

    def on_trial_add(self, trial):
        if not self._brackets or self._brackets[-1].full():
            self._brackets.append(
                _Bracket(self._next_s, self.s_max, self.max_t, self.eta))
            self._next_s = (self._next_s - 1 if self._next_s > 0
                            else self.s_max)
        bracket = self._brackets[-1]
        bracket.add(trial.trial_id)
        self._by_trial[trial.trial_id] = bracket

    def on_result(self, trial, result: dict) -> str:
        bracket = self._by_trial.get(trial.trial_id)
        if bracket is None:
            return CONTINUE
        t = result.get(self.time_attr, 0)
        if t < bracket.milestone:
            return CONTINUE
        if bracket.milestone >= self.max_t:
            bracket.remove(trial.trial_id)
            return STOP
        bracket.record_pause(trial.trial_id, self.score(result))
        return PAUSE

    def on_search_exhausted(self):
        self._no_more_trials = True

    def paused_actions(self, paused_trials) -> Dict[str, str]:
        actions: Dict[str, str] = {}
        for bracket in self._brackets:
            if bracket.ready_to_halve(self._no_more_trials):
                actions.update(bracket.halve())
        return actions

    def on_trial_complete(self, trial, result):
        bracket = self._by_trial.pop(trial.trial_id, None)
        if bracket is not None:
            bracket.remove(trial.trial_id)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is below the median of the running
    averages of completed/running trials at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(self.score(result))
        if t <= self.grace_period:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._avgs.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(hist)
        return STOP if best < median else CONTINUE


class ExploitDirective:
    """PBT decision: restore from `source_trial_id`'s checkpoint and adopt
    `new_config`."""

    def __init__(self, source_trial_id: str, new_config: dict):
        self.source_trial_id = source_trial_id
        self.new_config = new_config


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py:PopulationBasedTraining).

    Every perturbation_interval, a bottom-quantile trial exploits a
    top-quantile trial's checkpoint and perturbs hyperparameters in
    hyperparam_mutations (×1.2 / ×0.8 for numeric, resample for lists).
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self._latest: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    def _perturb(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if isinstance(spec, list):
                new[key] = self.rng.choice(spec)
            elif callable(spec):
                new[key] = spec()
            else:
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                new[key] = new[key] * factor
        return new

    def on_result(self, trial, result: dict):
        t = result.get(self.time_attr, 0)
        self._latest[trial.trial_id] = self.score(result)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        if len(self._latest) < 2:
            return CONTINUE
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and top:
            source = self.rng.choice(top)
            if source != trial.trial_id:
                return ExploitDirective(source, self._perturb(trial.config))
        return CONTINUE

    def on_trial_complete(self, trial, result):
        self._latest.pop(trial.trial_id, None)


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: schedulers/pb2.py,
    Parker-Holder et al., NeurIPS 2020). PBT's exploit step, but the
    new hyperparameters come from a GP-bandit over the continuous
    hyperparameter box instead of random multiply-by-1.2/0.8: every
    perturbation window contributes an observation (normalized config →
    score improvement), a numpy RBF-kernel GP fits them (no GPy
    dependency — the posterior is a dense solve over at most
    ``max_observations`` points), and a UCB acquisition over sampled
    candidates picks where to go next. Falls back to uniform sampling
    until enough observations exist.
    """

    def __init__(self, *, hyperparam_bounds: Dict[str, tuple],
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.5,
                 max_observations: int = 128,
                 seed: Optional[int] = None):
        super().__init__(
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={k: list(v)
                                  for k, v in hyperparam_bounds.items()},
            quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.max_obs = max_observations
        # Observations: (normalized config vector, score delta over one
        # perturbation window).
        self._obs_x: List[List[float]] = []
        self._obs_y: List[float] = []
        self._score_at_obs: Dict[str, float] = {}
        self._obs_time: Dict[str, float] = {}

    def _normalize(self, config: dict) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def _denormalize(self, x) -> dict:
        return {k: lo + float(xi) * (hi - lo)
                for (k, (lo, hi)), xi in zip(self.bounds.items(), x)}

    def on_result(self, trial, result):
        t = result.get(self.time_attr, 0)
        decision = super().on_result(trial, result)
        s = self.score(result)
        tid = trial.trial_id
        if isinstance(decision, ExploitDirective):
            # The trial is about to adopt another trial's checkpoint:
            # its next score is the SOURCE's, and crediting that jump
            # to the GP-chosen config would flood the posterior with
            # spurious improvements. Re-baseline at the next result.
            self._score_at_obs.pop(tid, None)
            self._obs_time.pop(tid, None)
        elif tid not in self._score_at_obs:
            self._score_at_obs[tid] = s
            self._obs_time[tid] = t
        elif t - self._obs_time[tid] >= self.interval:
            self._obs_x.append(self._normalize(trial.config))
            self._obs_y.append(s - self._score_at_obs[tid])
            self._score_at_obs[tid] = s
            self._obs_time[tid] = t
            if len(self._obs_y) > self.max_obs:
                self._obs_x.pop(0)
                self._obs_y.pop(0)
        return decision

    def _perturb(self, config: dict) -> dict:
        import numpy as np

        new = dict(config)
        if len(self._obs_y) < 4:
            # Cold start: uniform exploration of the box.
            for k, (lo, hi) in self.bounds.items():
                new[k] = lo + self.rng.random() * (hi - lo)
            return new
        X = np.asarray(self._obs_x)
        y = np.asarray(self._obs_y)
        y_std = y.std() or 1.0
        y_n = (y - y.mean()) / y_std
        length, jitter = 0.3, 1e-4
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-d2 / (2 * length ** 2))
        K_inv = np.linalg.inv(K + jitter * np.eye(len(X)))
        # Candidates: random box samples + jittered current config.
        rng = np.random.default_rng(self.rng.randrange(1 << 30))
        cand = rng.random((128, len(self.bounds)))
        cur = np.asarray(self._normalize(config))
        cand[:16] = np.clip(cur + rng.normal(0, 0.1,
                                             (16, len(cur))), 0, 1)
        d2c = ((cand[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        Kc = np.exp(-d2c / (2 * length ** 2))
        mu = Kc @ K_inv @ y_n
        var = np.maximum(1.0 - np.einsum("ij,jk,ik->i", Kc, K_inv, Kc),
                         1e-9)
        ucb = mu + self.kappa * np.sqrt(var)
        best = cand[int(np.argmax(ucb))]
        new.update(self._denormalize(best))
        return new
