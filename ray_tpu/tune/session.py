"""In-trial session API: tune.report / tune.get_checkpoint.

Reference: python/ray/tune (air session); the function-trainable side of
trainable/function_trainable.py. A thread-local holds the active trial's
report channel — the user fn runs in a background thread inside the
trial actor.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


@dataclass
class _FnSession:
    report: Callable[[Dict[str, Any], Optional[Checkpoint]], None]
    checkpoint: Optional[Checkpoint]
    trial_id: str
    trial_dir: str


def _set_session(sess: Optional[_FnSession]):
    _local.session = sess


def _get_session() -> Optional[_FnSession]:
    return getattr(_local, "session", None)


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    sess = _get_session()
    if sess is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    sess.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    sess = _get_session()
    if sess is None:
        raise RuntimeError(
            "tune.get_checkpoint() called outside a Tune trial")
    return sess.checkpoint


def get_trial_id() -> str:
    sess = _get_session()
    return sess.trial_id if sess else ""


def get_trial_dir() -> str:
    sess = _get_session()
    return sess.trial_dir if sess else ""
