"""ray_tpu.tune — distributed hyperparameter tuning.

Reference capability: python/ray/tune (Tuner, search algorithms, trial
schedulers, experiment checkpointing).
"""

from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    SearchGenerator,
    Searcher,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.callback import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    TBXLoggerCallback,
)
from ray_tpu.tune.schedulers import (
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.session import (
    get_checkpoint,
    get_trial_dir,
    get_trial_id,
    report,
)
from ray_tpu.tune.trainable import Trainable
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "CSVLoggerCallback",
    "Callback",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "HyperBandScheduler",
    "JsonLoggerCallback",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "ResultGrid",
    "TBXLoggerCallback",
    "SearchGenerator",
    "Searcher",
    "Trainable",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_trial_dir",
    "get_trial_id",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "report",
    "sample_from",
    "uniform",
]
