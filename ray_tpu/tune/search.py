"""Search spaces and search algorithms.

Reference: python/ray/tune/search/ — sample.py (Categorical/Float/Integer
domains, tune.choice/uniform/...), basic_variant.py (BasicVariantGenerator
expanding grid_search across random samples), concurrency_limiter.py.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, lower, upper):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower, upper, base=10):
        self.lower, self.upper, self.base = lower, upper, base

    def sample(self, rng):
        lo = math.log(self.lower, self.base)
        hi = math.log(self.upper, self.base)
        return self.base ** rng.uniform(lo, hi)


class RandInt(Domain):
    def __init__(self, lower, upper):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class QUniform(Domain):
    def __init__(self, lower, upper, q):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return round(v / self.q) * self.q


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower, upper) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower, upper, base=10) -> LogUniform:
    return LogUniform(lower, upper, base)


def randint(lower, upper) -> RandInt:
    return RandInt(lower, upper)


def quniform(lower, upper, q) -> QUniform:
    return QUniform(lower, upper, q)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def sample_from(fn):
    """Lazy sample depending on the rest of the config (spec)."""

    class _SampleFrom(Domain):
        def __init__(self, f):
            self.fn = f

        def sample(self, rng):
            raise RuntimeError("resolved separately")

    return _SampleFrom(fn)


# ---------------------------------------------------------------------------


def _walk(space: Dict[str, Any], prefix=()):
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict):
            yield from _walk(v, path)
        else:
            yield path, v


def _set_path(cfg: dict, path: tuple, value):
    cur = cfg
    for k in path[:-1]:
        cur = cur.setdefault(k, {})
    cur[path[-1]] = value


def _deep_copy_static(space):
    if isinstance(space, dict):
        return {k: _deep_copy_static(v) for k, v in space.items()}
    return space


class SearchAlgorithm:
    """Base: yields trial configs (reference: search/search_algorithm.py).

    ``next_configs`` is polled every controller loop iteration; return a
    batch of new configs, or None/[] when nothing new is available right
    now. The controller reports back trial ids (in emission order) via
    ``on_trials_created``, then intermediate results and completions.
    """

    def set_metric(self, metric: Optional[str], mode: str):
        self.metric, self.mode = metric, mode

    def next_configs(self) -> Optional[List[dict]]:
        raise NotImplementedError

    def is_finished(self) -> bool:
        """True once the search will produce no further configs. Used by
        synchronous schedulers (HyperBand) to close underfilled brackets;
        False (the conservative default) just defers to the controller's
        stall guard."""
        return False

    def on_trials_created(self, trial_ids: List[str]):
        pass

    def on_trial_result(self, trial_id: str, result: dict):
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        pass


class BasicVariantGenerator(SearchAlgorithm):
    """Grid × random expansion (reference: search/basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._emitted = False

    def next_configs(self) -> Optional[List[dict]]:
        if self._emitted:
            return None
        self._emitted = True
        grid_axes = []
        for path, v in _walk(self.space):
            if isinstance(v, GridSearch):
                grid_axes.append((path, v.values))
        configs = []
        grid_combos = (itertools.product(*[vals for _, vals in grid_axes])
                       if grid_axes else [()])
        for combo in grid_combos:
            for _ in range(self.num_samples):
                cfg = _deep_copy_static(self.space)
                for (path, _), val in zip(grid_axes, combo):
                    _set_path(cfg, path, val)
                for path, v in _walk(self.space):
                    if (isinstance(v, Domain)
                            and type(v).__name__ != "_SampleFrom"):
                        _set_path(cfg, path, v.sample(self.rng))
                # resolve sample_from last (may reference sampled values)
                for path, v in _walk(self.space):
                    if type(v).__name__ == "_SampleFrom":
                        _set_path(cfg, path, v.fn(cfg))
                configs.append(cfg)
        return configs

    def is_finished(self) -> bool:
        return self._emitted


class Searcher:
    """Adapter base for external optimizers (reference:
    python/ray/tune/search/searcher.py:Searcher).

    Subclass this to plug any sequential optimizer (Bayesian, TPE,
    annealing, a vendor library) into Tune: implement ``suggest`` to
    propose a config for a new trial id and ``on_trial_complete`` to
    feed the observed metric back. Wrap with ``SearchGenerator`` (or
    pass directly to TuneConfig.search_alg, which wraps automatically).
    """

    FINISHED = "FINISHED"

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              config: Optional[dict] = None) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Any]:
        """Return a config dict, None (nothing available right now), or
        Searcher.FINISHED (the search space is exhausted)."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict] = None,
                          error: bool = False):
        pass


class SearchGenerator(SearchAlgorithm):
    """Drives a ``Searcher`` through the SearchAlgorithm interface
    (reference: tune/search/search_generator.py:SearchGenerator).

    Suggests up to ``num_samples`` trials, pairing each suggestion with
    the controller-assigned trial id via ``on_trials_created`` so
    completion feedback reaches the searcher under the id it suggested
    for.
    """

    def __init__(self, searcher: Searcher,
                 num_samples: Optional[int] = 1):
        self.searcher = searcher
        # None = "not set yet": Tuner.fit fills in TuneConfig.num_samples
        # (used when ConcurrencyLimiter wraps a bare Searcher).
        self.num_samples = num_samples
        self._suggested = 0
        self._finished = False
        self._unpaired: List[str] = []   # searcher ids awaiting trial ids
        self._id_map: Dict[str, str] = {}  # trial_id -> searcher id

    def set_metric(self, metric, mode):
        super().set_metric(metric, mode)
        self.searcher.set_search_properties(metric, mode)

    def next_configs(self) -> Optional[List[dict]]:
        out = []
        limit = self.num_samples if self.num_samples is not None else 1
        while not self._finished and self._suggested < limit:
            sid = f"suggest_{self._suggested:05d}"
            cfg = self.searcher.suggest(sid)
            if cfg is None:
                break
            if cfg is Searcher.FINISHED or cfg == Searcher.FINISHED:
                self._finished = True
                break
            self._suggested += 1
            self._unpaired.append(sid)
            out.append(dict(cfg))
        return out or None

    def is_finished(self) -> bool:
        limit = self.num_samples if self.num_samples is not None else 1
        return self._finished or self._suggested >= limit

    def on_trials_created(self, trial_ids: List[str]):
        for tid in trial_ids:
            if self._unpaired:
                self._id_map[tid] = self._unpaired.pop(0)

    def on_trial_result(self, trial_id, result):
        sid = self._id_map.get(trial_id)
        if sid is not None:
            self.searcher.on_trial_result(sid, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        sid = self._id_map.get(trial_id)
        if sid is not None:
            self.searcher.on_trial_complete(sid, result, error=error)


class ConcurrencyLimiter(SearchAlgorithm):
    """Caps concurrent trials from a wrapped searcher (reference:
    search/concurrency_limiter.py). The controller reads max_concurrent.
    Accepts a SearchAlgorithm or a bare ``Searcher`` (wrapped in a
    SearchGenerator automatically, matching the reference API)."""

    def __init__(self, searcher, max_concurrent: int):
        if isinstance(searcher, Searcher):
            searcher = SearchGenerator(searcher, num_samples=None)
        self.searcher = searcher
        self.max_concurrent = max_concurrent

    def set_metric(self, metric, mode):
        self.searcher.set_metric(metric, mode)

    def next_configs(self):
        return self.searcher.next_configs()

    def is_finished(self):
        return self.searcher.is_finished()

    def on_trials_created(self, trial_ids):
        self.searcher.on_trials_created(trial_ids)

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self.searcher.on_trial_complete(trial_id, result, error)
