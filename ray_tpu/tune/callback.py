"""Experiment callbacks + logger callbacks.

Reference: python/ray/tune/callback.py (Callback hook points invoked by
the trial controller) and tune/logger/ — csv.py (CSVLoggerCallback,
per-trial progress.csv), json.py (JsonLoggerCallback, result.json lines
+ params.json), tensorboardx.py (TBXLoggerCallback, gated on the
optional tensorboardX dependency). W&B/MLflow integrations are declared
out in PARITY.md (external services).

Callbacks are driver-side: they run inside the TuneController loop, so
they see every result in order and must stay cheap.
"""

from __future__ import annotations

import csv
import json
import logging
import os
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class Callback:
    """Hook points (reference: tune/callback.py:Callback)."""

    def setup(self, experiment_dir: str) -> None:
        pass

    def on_trial_start(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_trial_result(self, iteration: int, trials: List, trial,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, iteration: int, trials: List,
                          trial) -> None:
        pass

    def on_trial_error(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_checkpoint(self, iteration: int, trials: List, trial,
                      checkpoint_path: str) -> None:
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


class _PerTrialFileCallback(Callback):
    """Shared plumbing: lazily opened per-trial files under the trial
    dir, closed at trial end/experiment end."""

    def __init__(self):
        self._files: Dict[str, Any] = {}

    def _open(self, trial, filename: str, mode: str = "a"):
        f = self._files.get(trial.trial_id)
        if f is None:
            os.makedirs(trial.trial_dir, exist_ok=True)
            f = open(os.path.join(trial.trial_dir, filename), mode)
            self._files[trial.trial_id] = f
        return f

    def _close(self, trial) -> None:
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()

    def on_trial_complete(self, iteration, trials, trial):
        self._close(trial)

    def on_trial_error(self, iteration, trials, trial):
        self._close(trial)

    def on_experiment_end(self, trials):
        for f in self._files.values():
            f.close()
        self._files.clear()


def _scalar_items(result: Dict[str, Any]):
    for k, v in result.items():
        if isinstance(v, (int, float, bool, str)) or v is None:
            yield k, v


class CSVLoggerCallback(_PerTrialFileCallback):
    """progress.csv per trial (reference: tune/logger/csv.py). The
    header is fixed by the first result; later keys are dropped (same
    contract as the reference's CSV logger)."""

    def __init__(self):
        super().__init__()
        self._writers: Dict[str, csv.DictWriter] = {}

    def on_trial_result(self, iteration, trials, trial, result):
        f = self._open(trial, "progress.csv")
        w = self._writers.get(trial.trial_id)
        row = dict(_scalar_items(result))
        if w is None:
            existing = None
            if f.tell() > 0:
                # Resumed experiment appending to a prior run's file:
                # reuse its header instead of writing a second one
                # mid-file.
                with open(f.name) as rf:
                    existing = next(csv.reader(rf), None)
            w = csv.DictWriter(f, fieldnames=existing or list(row),
                               extrasaction="ignore")
            if existing is None:
                w.writeheader()
            self._writers[trial.trial_id] = w
        w.writerow(row)
        f.flush()

    def on_trial_complete(self, iteration, trials, trial):
        self._writers.pop(trial.trial_id, None)
        super().on_trial_complete(iteration, trials, trial)

    def on_trial_error(self, iteration, trials, trial):
        self._writers.pop(trial.trial_id, None)
        super().on_trial_error(iteration, trials, trial)


class JsonLoggerCallback(_PerTrialFileCallback):
    """result.json (one JSON object per line) + params.json with the
    trial config (reference: tune/logger/json.py)."""

    def on_trial_start(self, iteration, trials, trial):
        os.makedirs(trial.trial_dir, exist_ok=True)
        params = {k: v for k, v in trial.config.items()
                  if isinstance(v, (int, float, bool, str, list, dict))
                  or v is None}
        with open(os.path.join(trial.trial_dir, "params.json"),
                  "w") as f:
            json.dump(params, f, indent=1)

    def on_trial_result(self, iteration, trials, trial, result):
        f = self._open(trial, "result.json")
        f.write(json.dumps(dict(_scalar_items(result))) + "\n")
        f.flush()


class TBXLoggerCallback(Callback):
    """TensorBoard scalars via tensorboardX when installed (reference:
    tune/logger/tensorboardx.py); a no-op with a one-time warning
    otherwise — the dependency is optional and absent from slim
    images."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}
        self._available: Optional[bool] = None

    def _writer(self, trial):
        if self._available is None:
            try:
                import tensorboardX  # noqa: F401

                self._available = True
            except ImportError:
                self._available = False
                logger.warning(
                    "tensorboardX is not installed; TBXLoggerCallback "
                    "is a no-op")
        if not self._available:
            return None
        w = self._writers.get(trial.trial_id)
        if w is None:
            from tensorboardX import SummaryWriter

            w = SummaryWriter(logdir=trial.trial_dir)
            self._writers[trial.trial_id] = w
        return w

    def on_trial_result(self, iteration, trials, trial, result):
        w = self._writer(trial)
        if w is None:
            return
        step = result.get("training_iteration", iteration)
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)

    def on_trial_complete(self, iteration, trials, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()

    on_trial_error = on_trial_complete

    def on_experiment_end(self, trials):
        for w in self._writers.values():
            w.close()
        self._writers.clear()


class CallbackList:
    """Fans controller events out to callbacks; one failing callback
    logs and never breaks the experiment."""

    def __init__(self, callbacks: Optional[List[Callback]]):
        self.callbacks = list(callbacks or [])

    def __bool__(self):
        return bool(self.callbacks)

    def fire(self, hook: str, *args) -> None:
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(*args)
            except Exception:
                logger.exception("tune callback %s.%s failed",
                                 type(cb).__name__, hook)
