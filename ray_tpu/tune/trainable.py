"""Trainables: the unit of execution for a Tune trial.

Reference: python/ray/tune/trainable/trainable.py (class Trainable:
setup/step/save_checkpoint/load_checkpoint/reset_config) and
function_trainable.py (function API driven by ``tune.report`` from a
background thread, results handed over a queue). Both kinds run inside
one actor per trial; the controller polls ``next_result``.
"""

from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

DONE = "__trial_done__"


class Trainable:
    """Class trainable API (reference: trainable.py:Trainable)."""

    def setup(self, config: dict) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Return True if the trainable supports in-place config reset
        (used by PBT exploit to avoid a full actor restart)."""
        return False

    def cleanup(self) -> None:
        pass


class _TrialRunner:
    """Actor body hosting one trial (class or function trainable).

    The controller drives it via ``next_result`` calls — one per reported
    result — so scheduler decisions (stop / exploit) apply between steps.
    """

    def __init__(self, trainable_spec, config: dict, trial_dir: str,
                 trial_id: str):
        os.makedirs(trial_dir, exist_ok=True)
        self.config = dict(config)
        self.trial_dir = trial_dir
        self.trial_id = trial_id
        self.iteration = 0
        self._ckpt_seq = 0
        self._restore_path: Optional[str] = None
        self._fn: Optional[Callable] = None
        self._cls_instance: Optional[Trainable] = None
        self._thread: Optional[threading.Thread] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._spec = trainable_spec
        if isinstance(trainable_spec, type) and issubclass(trainable_spec,
                                                           Trainable):
            self._cls_instance = trainable_spec()
            self._cls_instance.setup(self.config)
        elif callable(trainable_spec):
            self._fn = trainable_spec
        else:
            raise TypeError(f"bad trainable: {trainable_spec!r}")

    # -- function-trainable plumbing -----------------------------------
    def _run_fn(self):
        from ray_tpu.tune import session

        token = session._FnSession(
            report=self._fn_report,
            checkpoint=(Checkpoint(self._restore_path)
                        if self._restore_path else None),
            trial_id=self.trial_id,
            trial_dir=self.trial_dir,
        )
        session._set_session(token)
        try:
            self._fn(self.config)
            self._queue.put((DONE, None))
        except Exception:
            self._queue.put(("__error__", traceback.format_exc()))
        finally:
            session._set_session(None)

    def _fn_report(self, metrics: Dict[str, Any],
                   checkpoint: Optional[Checkpoint]):
        self._queue.put(("result", (dict(metrics),
                                    checkpoint.path if checkpoint else None)))

    # -- controller-facing API -----------------------------------------
    def next_result(self) -> Dict[str, Any]:
        """Blocking: produce the next reported result for this trial."""
        if self._cls_instance is not None:
            metrics = self._cls_instance.step()
            self.iteration += 1
            out = dict(metrics)
            out.setdefault("training_iteration", self.iteration)
            out["trial_id"] = self.trial_id
            out["done"] = bool(out.get("done", False))
            return out
        if self._thread is None:
            self._thread = threading.Thread(target=self._run_fn, daemon=True)
            self._thread.start()
        kind, payload = self._queue.get()
        if kind == DONE:
            return {"done": True, "trial_id": self.trial_id}
        if kind == "__error__":
            raise RuntimeError(f"trial fn failed:\n{payload}")
        metrics, ckpt_path = payload
        self.iteration += 1
        metrics.setdefault("training_iteration", self.iteration)
        metrics["trial_id"] = self.trial_id
        metrics["done"] = bool(metrics.get("done", False))
        if ckpt_path:
            metrics["__checkpoint_path__"] = ckpt_path
        return metrics

    def save(self) -> Optional[str]:
        """Class trainables: write a checkpoint dir and return its path."""
        if self._cls_instance is None:
            return None
        path = os.path.join(self.trial_dir,
                            f"checkpoint_{self._ckpt_seq:06d}")
        self._ckpt_seq += 1
        os.makedirs(path, exist_ok=True)
        self._cls_instance.save_checkpoint(path)
        # Runner-level meta so a restarted actor (pause/resume, PBT
        # exploit) keeps counting training_iteration from where the
        # checkpoint left off instead of from zero.
        with open(os.path.join(path, ".runner_meta"), "w") as f:
            f.write(f"{self.iteration} {self._ckpt_seq}")
        return path

    def restore(self, checkpoint_path: str) -> None:
        if self._cls_instance is not None:
            self._cls_instance.load_checkpoint(checkpoint_path)
            meta = os.path.join(checkpoint_path, ".runner_meta")
            if os.path.exists(meta):
                with open(meta) as f:
                    it, seq = f.read().split()
                self.iteration = int(it)
                self._ckpt_seq = int(seq)
        else:
            # Applied on (re)start: exposed to the fn via
            # tune.get_checkpoint().
            self._restore_path = checkpoint_path

    def reset(self, new_config: dict) -> bool:
        """PBT exploit path for class trainables."""
        self.config = dict(new_config)
        if self._cls_instance is not None:
            return bool(self._cls_instance.reset_config(self.config))
        return False

    def get_config(self) -> dict:
        return self.config

    def stop(self) -> None:
        if self._cls_instance is not None:
            self._cls_instance.cleanup()
