"""TuneController: the trial-driving event loop.

Reference: python/ray/tune/execution/tune_controller.py:72 — owns trial
lifecycle (PENDING → RUNNING → TERMINATED/ERROR), starts trial actors
under resource constraints, consumes results, applies scheduler
decisions, persists experiment state for resume. One actor per trial;
``next_result`` futures are multiplexed with ``ray_tpu.wait``.
"""

from __future__ import annotations

import json
import logging
import os
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import (
    CONTINUE,
    PAUSE,
    RESUME,
    STOP,
    ExploitDirective,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.callback import CallbackList
from ray_tpu.tune.search import ConcurrencyLimiter, SearchAlgorithm
from ray_tpu.tune.trainable import _TrialRunner

logger = logging.getLogger(__name__)

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    trial_id: str
    config: dict
    trial_dir: str
    state: str = PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    actor: Any = None
    future: Any = None
    retries: int = 0
    # Restart backoff (FailureConfig.restart_backoff_s): a retried
    # trial stays PENDING but is not started before this monotonic
    # time, so the controller loop never sleeps on its behalf.
    retry_at: float = 0.0


class TuneController:
    def __init__(self, trainable, *, search_alg: SearchAlgorithm,
                 scheduler: Optional[TrialScheduler],
                 metric: Optional[str], mode: str,
                 run_config: RunConfig, max_concurrent: int,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 checkpoint_freq: int = 0,
                 max_failures: Optional[int] = None,
                 experiment_dir: Optional[str] = None):
        self.trainable = trainable
        self.search_alg = search_alg
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        if metric:
            self.scheduler.set_metric(metric, mode)
            self.search_alg.set_metric(metric, mode)
        self.run_config = run_config
        if isinstance(search_alg, ConcurrencyLimiter):
            max_concurrent = min(max_concurrent, search_alg.max_concurrent)
        self.max_concurrent = max_concurrent
        self.resources = resources_per_trial or {"num_cpus": 1}
        self.checkpoint_freq = checkpoint_freq
        # Trial-level failure policy comes from RunConfig.failure_config
        # (reference semantics) unless explicitly overridden.
        failure = run_config.failure_config
        if max_failures is None:
            max_failures = failure.max_failures if failure else 0
        self.max_failures = max_failures
        self.restart_backoff_s = (
            failure.restart_backoff_s if failure else 0.0)
        name = run_config.name or f"tune_{int(time.time())}"
        self.exp_dir = experiment_dir or os.path.join(
            run_config.resolved_storage_path(), name)
        os.makedirs(self.exp_dir, exist_ok=True)
        self.trials: List[Trial] = []
        self._counter = 0
        self._iteration = 0  # controller loop ticks, for callbacks
        self.callbacks = CallbackList(run_config.callbacks)
        self.callbacks.fire("setup", self.exp_dir)

    # -- lifecycle ------------------------------------------------------
    def _new_trials(self):
        configs = self.search_alg.next_configs()
        if not configs:
            return
        created = []
        for cfg in configs:
            self._counter += 1
            tid = f"trial_{self._counter:05d}"
            trial = Trial(
                trial_id=tid, config=cfg,
                trial_dir=os.path.join(self.exp_dir, tid))
            self.trials.append(trial)
            created.append(tid)
            self.scheduler.on_trial_add(trial)
        self.search_alg.on_trials_created(created)

    def _start_trial(self, trial: Trial):
        actor_cls = ray_tpu.remote(_TrialRunner).options(**self.resources)
        trial.actor = actor_cls.remote(
            self.trainable, trial.config, trial.trial_dir, trial.trial_id)
        if trial.checkpoint_path:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint_path))
        trial.state = RUNNING
        self.callbacks.fire("on_trial_start", self._iteration,
                            self.trials, trial)
        trial.future = trial.actor.next_result.remote()

    def _stop_trial(self, trial: Trial, state: str, error: str = None):
        trial.state = state
        trial.error = error
        trial.future = None
        if trial.actor is not None:
            try:
                ray_tpu.get(trial.actor.stop.remote(), timeout=5)
            except Exception:
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        self.search_alg.on_trial_complete(
            trial.trial_id, trial.last_result, error=state == ERROR)
        self.scheduler.on_trial_complete(trial, trial.last_result)
        self.callbacks.fire(
            "on_trial_error" if state == ERROR else "on_trial_complete",
            self._iteration, self.trials, trial)

    def _pause_trial(self, trial: Trial):
        """Checkpoint and release the trial's actor; the scheduler later
        resumes (-> PENDING, restored from the checkpoint) or stops it."""
        self._maybe_checkpoint(trial, force=True)
        if trial.checkpoint_path is None:
            # Function trainables only checkpoint through
            # tune.report(checkpoint=...); without one, resume restarts
            # the function from scratch (reference semantics — its
            # HyperBand/PBT docs require checkpointable trainables).
            logger.warning(
                "pausing trial %s without a checkpoint; it will restart "
                "from iteration 0 on resume. Report checkpoints from the "
                "trainable (or use a class Trainable) with "
                "HyperBand/PBT.", trial.trial_id)
        trial.state = PAUSED
        trial.future = None
        if trial.actor is not None:
            try:
                ray_tpu.get(trial.actor.stop.remote(), timeout=5)
            except Exception:
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def _apply_paused_actions(self):
        paused = [t for t in self.trials if t.state == PAUSED]
        if not paused:
            return
        actions = self.scheduler.paused_actions(paused)
        for t in paused:
            act = actions.get(t.trial_id)
            if act == RESUME:
                t.state = PENDING
            elif act == STOP:
                self._stop_trial(t, TERMINATED)
                self._save_state()

    def _maybe_checkpoint(self, trial: Trial, force: bool = False):
        """Class trainables: periodic checkpoint via actor.save()."""
        it = trial.last_result.get("training_iteration", 0)
        due = (self.checkpoint_freq and it
               and it % self.checkpoint_freq == 0)
        if not (due or force) or trial.actor is None:
            return
        try:
            path = ray_tpu.get(trial.actor.save.remote(), timeout=60)
            if path:
                trial.checkpoint_path = path
                self.callbacks.fire("on_checkpoint", self._iteration,
                                    self.trials, trial, path)
        except Exception:
            logger.warning("checkpoint of %s failed", trial.trial_id)

    # -- exploit (PBT) --------------------------------------------------
    def _exploit(self, trial: Trial, directive: ExploitDirective):
        source = next((t for t in self.trials
                       if t.trial_id == directive.source_trial_id), None)
        if source is None:
            trial.future = trial.actor.next_result.remote()
            return
        src_ckpt = None
        if source.actor is not None:
            try:
                src_ckpt = ray_tpu.get(source.actor.save.remote(),
                                       timeout=60)
            except Exception:
                src_ckpt = None
        src_ckpt = src_ckpt or source.checkpoint_path
        trial.config = directive.new_config
        if src_ckpt is None:
            trial.future = trial.actor.next_result.remote()
            return
        in_place = False
        try:
            in_place = ray_tpu.get(
                trial.actor.reset.remote(directive.new_config), timeout=30)
        except Exception:
            in_place = False
        if not in_place:
            # Restart the actor with the mutated config + source ckpt.
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            actor_cls = ray_tpu.remote(_TrialRunner).options(
                **self.resources)
            trial.actor = actor_cls.remote(
                self.trainable, trial.config, trial.trial_dir,
                trial.trial_id)
        ray_tpu.get(trial.actor.restore.remote(src_ckpt))
        trial.checkpoint_path = src_ckpt
        trial.future = trial.actor.next_result.remote()

    # -- main loop ------------------------------------------------------
    def run(self) -> List[Trial]:
        self._new_trials()
        search_exhausted = False
        last_forced: Optional[frozenset] = None
        while True:
            self._iteration += 1
            self._new_trials()
            if not search_exhausted and self.search_alg.is_finished():
                search_exhausted = True
                self.scheduler.on_search_exhausted()
            self._apply_paused_actions()
            pending = [t for t in self.trials if t.state == PENDING]
            running = [t for t in self.trials if t.state == RUNNING]
            now = time.monotonic()
            for t in pending:
                if len(running) >= self.max_concurrent:
                    break
                if t.retry_at > now:
                    continue  # restart backoff window still open
                try:
                    self._start_trial(t)
                    running.append(t)
                except Exception as e:
                    # Start failures consume the same retry budget as
                    # runtime failures (a node that can't place the
                    # trial actor is a failure, not a terminal error) —
                    # _on_trial_error retries from the latest checkpoint
                    # or, once the budget is spent, notifies scheduler +
                    # searcher via _stop_trial so a HyperBand bracket
                    # can't wedge and a sequential searcher can't starve.
                    self._on_trial_error(t, e)
            running = [t for t in self.trials if t.state == RUNNING]
            pending = [t for t in self.trials if t.state == PENDING]
            if not running and pending:
                # Nothing running but startable trials remain — either
                # inside a backoff window (wait it out) or freshly
                # expired mid-iteration (sleep 0). Looping here instead
                # of falling through to the no-futures exit below is
                # what keeps a retried trial from being stranded in
                # PENDING forever.
                time.sleep(max(0.0, min(t.retry_at for t in pending)
                               - time.monotonic()))
                continue
            if not running and not pending:
                paused = [t for t in self.trials if t.state == PAUSED]
                if paused:
                    # Scheduler offered no action for any paused trial and
                    # nothing else can make progress (e.g. a bracket member
                    # died outside the scheduler's view): resume them all
                    # rather than hang. If the SAME set lands here again
                    # WITHOUT progress (same trials at the same
                    # iteration — a checkpointless trial re-pausing at
                    # one milestone forever), terminate it instead — a
                    # bounded guard, not a livelock. Trials that advanced
                    # between firings hash differently and get resumed.
                    ids = frozenset(
                        (t.trial_id,
                         t.last_result.get("training_iteration", 0))
                        for t in paused)
                    if ids == last_forced:
                        logger.warning(
                            "stall guard fired twice for the same %d "
                            "paused trial(s); terminating them",
                            len(paused))
                        for t in paused:
                            self._stop_trial(t, TERMINATED)
                        self._save_state()
                        continue
                    last_forced = ids
                    logger.warning(
                        "resuming %d paused trial(s) with no scheduler "
                        "action to avoid a stall", len(paused))
                    for t in paused:
                        t.state = PENDING
                    continue
                break
            futures = [t.future for t in running if t.future is not None]
            if not futures:
                break
            ready, _ = ray_tpu.wait(futures, num_returns=1, timeout=30.0)
            if not ready:
                continue
            fut = ready[0]
            trial = next(t for t in running if t.future is fut)
            try:
                result = ray_tpu.get(fut)
            except Exception as e:
                self._on_trial_error(trial, e)
                continue
            self._on_result(trial, result)
        self._save_state()
        self.callbacks.fire("on_experiment_end", self.trials)
        return self.trials

    def _on_result(self, trial: Trial, result: Dict[str, Any]):
        if result.get("done") and len(result) <= 2:
            # Function trainable finished without a final report.
            self._maybe_checkpoint(trial, force=bool(self.checkpoint_freq))
            self._stop_trial(trial, TERMINATED)
            self._save_state()
            return
        ckpt = result.pop("__checkpoint_path__", None)
        if ckpt:
            trial.checkpoint_path = ckpt
        trial.last_result = result
        trial.history.append(dict(result))
        self.search_alg.on_trial_result(trial.trial_id, result)
        self.callbacks.fire("on_trial_result", self._iteration,
                            self.trials, trial, result)
        self._maybe_checkpoint(trial)
        if self._stop_criteria_met(trial, result):
            self._maybe_checkpoint(trial, force=bool(self.checkpoint_freq))
            self._stop_trial(trial, TERMINATED)
            self._save_state()
            return
        if result.get("done"):
            self._maybe_checkpoint(trial, force=bool(self.checkpoint_freq))
            self._stop_trial(trial, TERMINATED)
            self._save_state()
            return
        decision = (self.scheduler.on_result(trial, result)
                    if self.metric else CONTINUE)
        if isinstance(decision, ExploitDirective):
            self._exploit(trial, decision)
        elif decision == PAUSE:
            self._pause_trial(trial)
            self._save_state()
        elif decision == STOP:
            self._maybe_checkpoint(trial, force=bool(self.checkpoint_freq))
            self._stop_trial(trial, TERMINATED)
            self._save_state()
        else:
            trial.future = trial.actor.next_result.remote()

    def _stop_criteria_met(self, trial: Trial, result: dict) -> bool:
        stop = self.run_config.stop
        if stop is None:
            return False
        if callable(stop):
            return bool(stop(trial.trial_id, result))
        return any(k in result and result[k] >= v for k, v in stop.items())

    def _on_trial_error(self, trial: Trial, error: Exception):
        logger.warning("trial %s failed: %s", trial.trial_id, error)
        if trial.retries < self.max_failures:
            from ray_tpu.util import telemetry

            trial.retries += 1
            if trial.actor is not None:
                try:
                    ray_tpu.kill(trial.actor)
                except Exception:
                    pass
            trial.actor = None
            # Back to PENDING: _start_trial restores from the trial's
            # latest checkpoint (trial.checkpoint_path), so the retry
            # resumes instead of restarting from scratch.
            trial.state = PENDING
            trial.future = None
            trial.retry_at = time.monotonic() + self.restart_backoff_s
            telemetry.inc("ray_tpu_tune_trial_retries_total")
            logger.info(
                "retrying trial %s (%d/%d) from checkpoint %s after "
                "%.1fs backoff", trial.trial_id, trial.retries,
                self.max_failures, trial.checkpoint_path or "<none>",
                self.restart_backoff_s)
        else:
            self._stop_trial(trial, ERROR, error=str(error))
        self._save_state()

    # -- persistence ----------------------------------------------------
    def _save_state(self):
        state = {
            "metric": self.metric,
            "mode": self.mode,
            "counter": self._counter,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config_repr": {k: v for k, v in t.config.items()
                                    if _jsonable(v)},
                    "state": t.state,
                    "last_result": {k: v for k, v in t.last_result.items()
                                    if _jsonable(v)},
                    "checkpoint_path": t.checkpoint_path,
                    "error": t.error,
                }
                for t in self.trials
            ],
        }
        tmp = os.path.join(self.exp_dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, indent=2)
        os.replace(tmp, os.path.join(self.exp_dir,
                                     "experiment_state.json"))
        # Full-fidelity configs for restore (config_repr above is a
        # human-readable JSON projection that drops non-JSON values).
        import pickle

        tmp2 = os.path.join(self.exp_dir, ".trial_configs.tmp")
        with open(tmp2, "wb") as f:
            pickle.dump({t.trial_id: t.config for t in self.trials}, f)
        os.replace(tmp2, os.path.join(self.exp_dir, "trial_configs.pkl"))

    def results(self) -> List[Result]:
        out = []
        for t in self.trials:
            out.append(Result(
                metrics=t.last_result,
                checkpoint=(Checkpoint(t.checkpoint_path)
                            if t.checkpoint_path else None),
                path=t.trial_dir,
                error=t.error,
                metrics_history=t.history,
            ))
        return out


def _jsonable(v) -> bool:
    return isinstance(v, (str, int, float, bool, type(None), list, dict))
