"""Tuner: the user-facing entry point.

Reference: python/ray/tune/tuner.py (Tuner.fit:347), tune_config.py
(TuneConfig), result_grid.py (ResultGrid). ``Tuner`` also accepts a
``JaxTrainer`` — the trainer becomes a function trainable whose
param_space key ``train_loop_config`` overrides the trainer's config,
mirroring the reference's trainer-as-Trainable wrapping
(train/base_trainer.py:747).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    SearchAlgorithm,
    SearchGenerator,
    Searcher,
)
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.tune_controller import TuneController, Trial


@dataclasses.dataclass
class TuneConfig:
    """Reference: python/ray/tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    search_alg: Optional[SearchAlgorithm] = None
    scheduler: Optional[TrialScheduler] = None
    checkpoint_freq: int = 0
    seed: Optional[int] = None

    def __post_init__(self):
        if self.mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")


class ResultGrid:
    """Reference: python/ray/tune/result_grid.py."""

    def __init__(self, results, metric: Optional[str], mode: str):
        self._results = list(results)
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric given to get_best_result")
        candidates = [r for r in self._results
                      if not r.error and metric in (r.metrics or {})]
        if not candidates:
            raise RuntimeError("no successful trial reported the metric")
        sign = 1 if mode == "max" else -1
        return max(candidates, key=lambda r: sign * r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics for r in self._results])


class Tuner:
    def __init__(self, trainable, *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 _experiment_dir: Optional[str] = None):
        from ray_tpu.train.trainer import JaxTrainer

        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial
        self._experiment_dir = _experiment_dir
        if isinstance(trainable, JaxTrainer):
            self.trainable = _trainer_as_trainable(trainable)
            # Trial actors only coordinate; the trainer's own worker
            # group claims the training resources.
            self.resources_per_trial = (resources_per_trial
                                        or {"num_cpus": 0.1})
        else:
            self.trainable = trainable

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        search_alg = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples, seed=tc.seed)
        if isinstance(search_alg, Searcher):
            search_alg = SearchGenerator(search_alg,
                                         num_samples=tc.num_samples)
        else:
            # A ConcurrencyLimiter wrapping a bare Searcher defers the
            # sample budget to TuneConfig.num_samples.
            inner = getattr(search_alg, "searcher", None)
            if (isinstance(inner, SearchGenerator)
                    and inner.num_samples is None):
                inner.num_samples = tc.num_samples
        controller = TuneController(
            self.trainable,
            search_alg=search_alg,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode,
            run_config=self.run_config,
            max_concurrent=tc.max_concurrent_trials,
            resources_per_trial=self.resources_per_trial,
            checkpoint_freq=tc.checkpoint_freq,
            experiment_dir=self._experiment_dir,
        )
        trials = controller.run()
        return ResultGrid(controller.results(), tc.metric, tc.mode)

    @classmethod
    def restore(cls, experiment_dir: str, trainable,
                *, tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None,
                resources_per_trial: Optional[Dict[str, float]] = None
                ) -> "Tuner":
        """Resume an interrupted experiment (reference: Tuner.restore).

        Terminated trials keep their recorded results; unfinished trials
        restart (from their last checkpoint if any) via a restorer search
        algorithm that replays the saved trial configs.
        """
        state_file = os.path.join(experiment_dir, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        # Prefer the pickled full-fidelity configs; the JSON config_repr
        # drops non-JSON-serializable values.
        cfg_file = os.path.join(experiment_dir, "trial_configs.pkl")
        if os.path.exists(cfg_file):
            import pickle

            with open(cfg_file, "rb") as f:
                full = pickle.load(f)
            for t in state["trials"]:
                if t["trial_id"] in full:
                    t["config_repr"] = full[t["trial_id"]]
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config,
                    resources_per_trial=resources_per_trial,
                    _experiment_dir=experiment_dir)
        tuner._restore_state = state
        tuner.fit = tuner._restored_fit  # type: ignore[method-assign]
        return tuner

    def _restored_fit(self) -> ResultGrid:
        state = self._restore_state
        tc = self.tune_config

        class _Restorer(SearchAlgorithm):
            def __init__(self, trials):
                self._trials = trials
                self._emitted = False

            def set_metric(self, metric, mode):
                pass

            def next_configs(self):
                if self._emitted:
                    return None
                self._emitted = True
                return [t["config_repr"] for t in self._trials
                        if t["state"] not in ("TERMINATED",)]

        unfinished = [t for t in state["trials"]
                      if t["state"] != "TERMINATED"]
        controller = TuneController(
            self.trainable,
            search_alg=_Restorer(state["trials"]),
            scheduler=tc.scheduler,
            metric=tc.metric or state.get("metric"),
            mode=tc.mode if tc.metric else state.get("mode", "max"),
            run_config=self.run_config,
            max_concurrent=tc.max_concurrent_trials,
            resources_per_trial=self.resources_per_trial,
            checkpoint_freq=tc.checkpoint_freq,
            experiment_dir=self._experiment_dir,
        )
        # Seed checkpoints so restarted trials resume, not restart.
        controller._new_trials()
        for trial, saved in zip(controller.trials, unfinished):
            trial.checkpoint_path = saved.get("checkpoint_path")
        trials = controller.run()
        results = controller.results()
        # Merge back terminated trials' recorded results.
        from ray_tpu.train.checkpoint import Checkpoint

        for t in state["trials"]:
            if t["state"] == "TERMINATED":
                results.append(Result(
                    metrics=t["last_result"],
                    checkpoint=(Checkpoint(t["checkpoint_path"])
                                if t.get("checkpoint_path") else None),
                    path=os.path.join(self._experiment_dir, t["trial_id"]),
                    error=t.get("error"),
                ))
        metric = tc.metric or state.get("metric")
        mode = tc.mode if tc.metric else state.get("mode", "max")
        return ResultGrid(results, metric, mode)


def _trainer_as_trainable(trainer):
    """Wrap a JaxTrainer so each trial runs trainer.fit with the trial's
    train_loop_config override (reference: base_trainer.py:747)."""
    import copy

    def _fit_fn(config: dict):
        from ray_tpu.tune import session as tune_session

        t = copy.copy(trainer)
        loop_cfg = dict(t.train_loop_config)
        loop_cfg.update(config.get("train_loop_config", config))
        t.train_loop_config = loop_cfg
        result = t.fit()
        if result.error:
            raise RuntimeError(result.error)
        metrics = dict(result.metrics or {})
        tune_session.report(metrics, checkpoint=result.checkpoint)

    return _fit_fn
