"""gRPC ingress for Serve deployments.

Reference: python/ray/serve/_private/proxy.py:542 (gRPCProxy — a second
ingress sharing the HTTP proxy's routing/assignment machinery). The
reference requires user-supplied protobuf servicers; here the ingress
is schema-light: a **generic unary service** at

    /ray_tpu.serve.UserDefinedService/<app_or_route>

whose request/response payloads are pickled Python values — any client
with grpcio calls deployments without compiling protos:

    import grpc, pickle
    ch = grpc.insecure_channel(addr)
    call = ch.unary_unary("/ray_tpu.serve.UserDefinedService/myapp")
    result = pickle.loads(call(pickle.dumps(((arg,), {}))))

Generator deployments get **server streaming** parity through a second
service name — each response message is one pickled chunk:

    call = ch.unary_stream(
        "/ray_tpu.serve.UserDefinedStreamingService/myapp")
    for msg in call(pickle.dumps(((arg,), {}))):
        chunk = pickle.loads(msg)

Routing reuses the Router (power-of-two-choices replica assignment,
multiplex-aware) exactly as the HTTP proxy does; the gRPC method name
selects the deployment by route prefix ("/<name>").
"""

from __future__ import annotations

import logging
import pickle
from concurrent import futures
from typing import Optional

logger = logging.getLogger(__name__)

SERVICE = "ray_tpu.serve.UserDefinedService"
STREAM_SERVICE = "ray_tpu.serve.UserDefinedStreamingService"


class GrpcProxy:
    """Runs inside the proxy actor next to the HTTP ingress.

    Security posture (r4 advisor): payloads are PICKLED, so the ingress
    must never be reachable by untrusted peers. Enforced here, not just
    documented:

    - binding anything but loopback requires a shared-secret token
      (``token=`` or ``RAY_TPU_SERVE_GRPC_TOKEN``) — a bare wide bind
      raises at startup;
    - when a token is set, every call must carry metadata
      ``("serve-token", <token>)`` (or ``authorization: Bearer <token>``)
      and unauthenticated calls are rejected with UNAUTHENTICATED
      before the request bytes are unpickled.
    """

    def __init__(self, get_router, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None):
        import os

        import grpc

        self._get_router = get_router
        self._token = token if token is not None else \
            os.environ.get("RAY_TPU_SERVE_GRPC_TOKEN") or None
        if host not in ("127.0.0.1", "localhost", "::1") \
                and not self._token:
            raise ValueError(
                f"refusing to bind the pickle-payload gRPC ingress to "
                f"non-loopback {host!r} without a shared secret — set "
                f"RAY_TPU_SERVE_GRPC_TOKEN (clients then send "
                f"('serve-token', <token>) metadata)")

        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                path = handler_call_details.method
                prefix = f"/{SERVICE}/"
                stream_prefix = f"/{STREAM_SERVICE}/"
                if path.startswith(stream_prefix):
                    target = path[len(stream_prefix):]
                    return grpc.unary_stream_rpc_method_handler(
                        lambda req, ctx: proxy._call_stream(
                            target, req, ctx))
                if not path.startswith(prefix):
                    return None
                target = path[len(prefix):]
                return grpc.unary_unary_rpc_method_handler(
                    lambda req, ctx: proxy._call(target, req, ctx))

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        logger.info("serve gRPC ingress on %s:%d", host, self.port)

    def _authorized(self, context) -> bool:
        if self._token is None:
            return True
        import hmac

        for k, v in (context.invocation_metadata() or ()):
            if k == "serve-token" and hmac.compare_digest(
                    str(v), self._token):
                return True
            if k == "authorization" and hmac.compare_digest(
                    str(v), f"Bearer {self._token}"):
                return True
        return False

    def _call(self, target: str, request: bytes, context):
        import grpc

        if not self._authorized(context):
            # Rejected BEFORE unpickling: the payload format is the
            # attack surface.
            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "missing or wrong serve-token metadata")
            return b""
        try:
            args, kwargs = pickle.loads(request) if request else ((), {})
        except Exception:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "request must be pickle.dumps((args, kwargs))")
            return b""
        router, key, _entry = self._route(target, context)
        model_id = ""
        for k, v in (context.invocation_metadata() or ()):
            if k == "serve_multiplexed_model_id":
                model_id = v
        call_kwargs = dict(kwargs)
        if model_id:
            call_kwargs["__serve_multiplexed_model_id"] = model_id
        import ray_tpu

        try:
            ref = router.assign(key, "__call__", tuple(args), call_kwargs)
            result = ray_tpu.get(ref, timeout=300)
        except Exception as e:
            logger.exception("grpc proxy call failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))
            return b""
        return pickle.dumps(result)

    def _route(self, target: str, context):
        """Longest-prefix route for a gRPC method name; aborts NOT_FOUND
        when nothing is deployed there."""
        import grpc

        router = self._get_router()
        key, entry = router.resolve_route(f"/{target}")
        if key is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no deployment routed at /{target}")
        return router, key, entry

    def _call_stream(self, target: str, request: bytes, context):
        """Server-streaming lane for generator deployments: yields one
        pickled message per chunk. Mid-stream failures terminate the RPC
        with INTERNAL carrying the error; calling it on a non-generator
        deployment is UNIMPLEMENTED."""
        import grpc

        if not self._authorized(context):
            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "missing or wrong serve-token metadata")
            return
        try:
            args, kwargs = pickle.loads(request) if request else ((), {})
        except Exception:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "request must be pickle.dumps((args, kwargs))")
            return
        router, key, entry = self._route(target, context)
        if not entry.get("stream"):
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                f"deployment at /{target} is not a generator "
                f"deployment; call the unary "
                f"/{SERVICE}/{target} method instead")
            return
        call_kwargs = dict(kwargs)
        for k, v in (context.invocation_metadata() or ()):
            if k == "serve_multiplexed_model_id":
                call_kwargs["__serve_multiplexed_model_id"] = v
        import ray_tpu

        try:
            gen = router.assign(key, "__call__", tuple(args),
                                call_kwargs, stream=True)
        except Exception as e:
            logger.exception("grpc stream assignment failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))
            return
        # Client cancellation tears the stream down replica-side too.
        context.add_callback(gen.close)
        from ray_tpu import exceptions as exc
        from ray_tpu.core.config import get_config

        # Same inter-chunk deadline as the HTTP proxy: a hung replica
        # keeps its connection alive, so only a chunk timeout turns
        # "silent hang" into a terminated RPC.
        chunk_timeout = get_config().serve_stream_chunk_timeout_s
        deadline_hit = False
        try:
            while True:
                try:
                    ref = gen.next_ready(timeout=chunk_timeout)
                except StopIteration:
                    break
                except exc.GetTimeoutError:
                    gen._release_reason = "deadline"
                    gen.close()
                    deadline_hit = True
                    break  # abort() raises; keep it outside this try
                yield pickle.dumps(ray_tpu.get(ref, timeout=300))
        except Exception as e:
            logger.exception("grpc stream failed mid-stream")
            gen.close()
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        if deadline_hit:
            context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"no chunk within {chunk_timeout:.0f}s (stream deadline)")

    def stop(self, grace: Optional[float] = 1.0):
        self._server.stop(grace)
