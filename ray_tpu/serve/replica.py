"""Replica actor: hosts one copy of a deployment's callable.

Reference: python/ray/serve/_private/replica.py — wraps the user class,
counts ongoing requests (the router's pow-2 signal), applies
reconfigure(user_config), and exposes a health check. TPU-first: an
optional ``warmup`` hook runs at startup so jit compilation happens
before the replica joins the routing table (reference gap: Serve TTFT on
accelerators is dominated by first-request compilation — SURVEY §7.3).
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional


class Replica:
    def __init__(self, serialized_callable, init_args, init_kwargs,
                 user_config, deployment_name: str, replica_id: str,
                 engine_config=None):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self.num_ongoing = 0
        self.total_served = 0
        self._started = time.time()
        self._serialized_callable = serialized_callable
        self._init_args = init_args
        self._init_kwargs = init_kwargs
        self._user_config = user_config
        self.callable = None
        # Continuous-batching engine (serve/engine/): constructed
        # lazily on the event loop once the callable exists — streams
        # then share the per-replica decode loop instead of running one
        # generator body per request.
        self._engine_cfg = engine_config
        self._engine = None
        # User __init__ is cold-start code — checkpoint reads, blocking
        # weight fetches (serve.fetch_weights pulling sharded arrays
        # through the device object plane), warmup jit — so it must NOT
        # run on this actor's event loop: a blocking ray_tpu.get() there
        # would deadlock the worker. Construction runs on the executor;
        # requests and health checks gate on the future (the controller
        # counts the replica ready only once check_health passes).
        self._built = asyncio.get_event_loop().run_in_executor(
            None, self._build)

    def _build(self):
        from ray_tpu.core import serialization as _ser

        cls_or_fn = _ser.loads_control(self._serialized_callable)
        if inspect.isclass(cls_or_fn):
            callable_ = cls_or_fn(*self._init_args,
                                  **(self._init_kwargs or {}))
        else:
            if self._init_args or self._init_kwargs:
                raise TypeError("function deployments take no init args")
            callable_ = cls_or_fn
        self.callable = callable_
        if self._user_config is not None:
            self._reconfigure_sync(self._user_config)
        warmup = getattr(callable_, "warmup", None)
        if callable(warmup):
            warmup()

    async def _ensure_built(self):
        # Shield: a cancelled request must not cancel construction for
        # every later request. Raises the user __init__ error, if any.
        await asyncio.shield(self._built)

    def _reconfigure_sync(self, user_config):
        fn = getattr(self.callable, "reconfigure", None)
        if fn is None:
            raise ValueError(
                f"{self.deployment_name}: user_config given but callable "
                "has no reconfigure() method")
        fn(user_config)

    async def reconfigure(self, user_config) -> None:
        await self._ensure_built()
        self._reconfigure_sync(user_config)

    def _resolve_fn(self, method_name: str):
        fn = getattr(self.callable, method_name, None)
        if fn is None and method_name == "__call__":
            fn = self.callable
        if fn is None:
            raise AttributeError(
                f"{self.deployment_name} has no method "
                f"{method_name!r}")
        return fn

    def _request_scope(self, kwargs: dict, label: str):
        """Shared per-request bookkeeping for the unary AND streaming
        lanes: pops the hidden serve kwargs, installs tracing + request
        context, and counts ongoing/served/latency in one place — the
        two lanes differ only in how they execute the callable. Yields
        a one-slot dict; set ``scope["status"] = "ok"`` on success."""
        import contextlib
        import uuid

        from ray_tpu.serve import context as _ctx
        from ray_tpu.util import profiler, telemetry, tracing

        model_id = kwargs.pop("__serve_multiplexed_model_id", "")
        trace_ctx = kwargs.pop("__serve_trace_ctx", None)

        @contextlib.contextmanager
        def scope_cm():
            # ExitStack so a raising request closes the span with the
            # real exception info (error status on otel spans).
            with contextlib.ExitStack() as stack:
                if trace_ctx is not None:
                    # The carrier's presence proves the driver enabled
                    # tracing (same contract as worker_main's task
                    # path).
                    tracing.setup_tracing("ray_tpu.serve.replica")
                    stack.enter_context(tracing.span(label, trace_ctx))
                request_id = uuid.uuid4().hex[:12]
                _ctx._set_request_context(_ctx.RequestContext(
                    multiplexed_model_id=model_id,
                    deployment=self.deployment_name,
                    request_id=request_id))
                # Profiler attribution: sampled stacks of this request
                # land under serve:<deployment> with the request id —
                # and, for @serve.multiplexed deployments, the model id
                # the request was routed for, so a hot model stands out
                # in the per-bucket sample counts.
                prof_labels = dict(
                    serve_request=request_id,
                    name=f"serve:{self.deployment_name}",
                    deployment=self.deployment_name)
                if model_id:
                    prof_labels["model_id"] = model_id
                prof_token = profiler.push_thread_context(**prof_labels)
                self.num_ongoing += 1
                t0 = time.perf_counter()
                scope = {"status": "error"}
                try:
                    yield scope
                finally:
                    profiler.pop_thread_context(prof_token)
                    self.num_ongoing -= 1
                    self.total_served += 1
                    telemetry.inc(
                        "ray_tpu_serve_replica_requests_total", 1,
                        {"deployment": self.deployment_name,
                         "status": scope["status"]})
                    telemetry.observe(
                        "ray_tpu_serve_replica_latency_seconds",
                        time.perf_counter() - t0,
                        {"deployment": self.deployment_name})

        return scope_cm()

    def _ensure_engine(self):
        if self._engine is None:
            from ray_tpu.serve.engine import ContinuousBatchingEngine

            self._engine = ContinuousBatchingEngine(
                self.callable, self._engine_cfg, self.deployment_name)
        return self._engine

    async def handle_request(self, method_name: str, args: tuple,
                             kwargs: dict) -> Any:
        await self._ensure_built()
        if self._engine_cfg is not None and method_name == "__call__":
            raise TypeError(
                f"{self.deployment_name} runs the continuous-batching "
                "engine; __call__ is streaming-only — use "
                "handle.options(stream=True).remote(...) (or the HTTP "
                "proxy, which streams engine deployments "
                "automatically)")
        with self._request_scope(
                kwargs, f"replica {self.deployment_name}") as scope:
            fn = self._resolve_fn(method_name)
            out = fn(*args, **kwargs)
            if inspect.isawaitable(out):
                out = await out
            if inspect.isgenerator(out) or inspect.isasyncgen(out):
                # Materializing a stream into one response would
                # defeat the generator; point at the streaming API.
                raise TypeError(
                    f"{self.deployment_name}.{method_name} returned "
                    "a generator from a non-streaming call; use "
                    "handle.options(stream=True).remote(...) (or "
                    "the HTTP proxy, which streams generator "
                    "deployments automatically)")
            scope["status"] = "ok"
            return out

    async def handle_request_streaming(self, method_name: str,
                                       args: tuple, kwargs: dict):
        """Streaming twin of ``handle_request``: an async-generator
        actor method executed with ``num_returns='streaming'`` — every
        yielded chunk rides the core stream_item lane to the caller.
        Sync and async user generators both work; replica metrics count
        the whole stream as one request."""
        await self._ensure_built()
        if self._engine_cfg is not None and method_name == "__call__":
            # Engine lane: the request joins the replica-wide decode
            # loop; chunks still ride the same per-request core stream
            # lane as classic generators (credit-based backpressure on
            # the consumer side pauses only this sequence upstream).
            with self._request_scope(
                    kwargs,
                    f"replica {self.deployment_name} engine") as scope:
                engine = self._ensure_engine()
                seq = engine.submit(args, kwargs)
                try:
                    async for chunk in engine.stream(seq):
                        yield chunk
                finally:
                    # Covers client disconnect / cancellation: the core
                    # lane cancels this async generator, which must
                    # evict the sequence from the running batch.
                    engine.cancel(seq)
                scope["status"] = "ok"
            return
        with self._request_scope(
                kwargs,
                f"replica {self.deployment_name} stream") as scope:
            fn = self._resolve_fn(method_name)
            out = fn(*args, **kwargs)
            if inspect.isawaitable(out):
                out = await out
            if inspect.isasyncgen(out):
                async for chunk in out:
                    yield chunk
            elif hasattr(out, "__next__"):
                # Sync generator on the replica loop: yields hand
                # control back between chunks, so health checks and
                # concurrent requests still interleave.
                for chunk in out:
                    yield chunk
            else:
                raise TypeError(
                    f"{self.deployment_name}.{method_name} was "
                    "called with stream=True but returned "
                    f"{type(out).__name__}, not a generator/async "
                    "generator")
            scope["status"] = "ok"

    async def metrics(self) -> Dict[str, Any]:
        out = {
            "replica_id": self.replica_id,
            "num_ongoing": self.num_ongoing,
            "total_served": self.total_served,
            "uptime_s": time.time() - self._started,
        }
        if self._engine is not None:
            # Autoscaling signals: batch occupancy + admission queue
            # depth feed the controller's scale decisions.
            out["engine"] = self._engine.stats()
        return out

    async def check_health(self) -> bool:
        # Still constructing: not ready yet (the controller's startup
        # grace covers cold starts). A failed construction re-raises the
        # user error here so the probe surfaces it.
        if not self._built.done():
            return False
        await self._ensure_built()
        # An engine whose loop died on a bug fails every request fast —
        # report unhealthy so the controller's restart machinery
        # replaces this replica instead of routing to a green corpse.
        if self._engine is not None and self._engine.failed:
            return False
        fn = getattr(self.callable, "check_health", None)
        if callable(fn):
            out = fn()
            if inspect.isawaitable(out):
                out = await out
            return bool(out) if out is not None else True
        return True

    async def prepare_shutdown(self, drain_timeout_s: float = 8.0
                               ) -> None:
        """Drain ongoing requests, then run the user cleanup hook — the
        worker process is force-killed afterwards, so finalizers would
        otherwise never run."""
        if self._engine is not None:
            # Drain first: a routine autoscale-down or redeploy must
            # not error live client streams. New submits shed fast,
            # in-flight sequences finish within the budget (the
            # controller bounds this whole call with
            # graceful_shutdown_timeout_s), then leftovers — e.g.
            # endless streams — fail terminally (an error chunk, never
            # a hang).
            self._engine.begin_drain()
            deadline = time.time() + max(0.0, drain_timeout_s)
            while not self._engine.idle and time.time() < deadline:
                await asyncio.sleep(0.02)
            await self._engine.shutdown()
        while self.num_ongoing > 0:
            await asyncio.sleep(0.02)
        try:
            await self._ensure_built()
        except Exception:
            return  # construction failed: nothing to clean up
        fn = getattr(self.callable, "__del__", None)
        if callable(fn):
            try:
                out = fn()
                if inspect.isawaitable(out):
                    await out
            except Exception:
                pass
