"""serve.run / serve.shutdown / status — the public control API.

Reference: python/ray/serve/api.py (serve.run:429, serve.delete,
serve.status, serve.start). The controller is a named detached async
actor (get-or-create), the HTTP proxy starts lazily on first run.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve import handle as handle_mod
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, build_specs
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.proxy import PROXY_NAME, ProxyActor

DEFAULT_APP_NAME = "default"


def _get_or_create_controller():
    return (ray_tpu.remote(ServeController)
            .options(name=CONTROLLER_NAME, lifetime="detached",
                     get_if_exists=True, num_cpus=0.1)
            .remote())


def start(*, http_host: str = "127.0.0.1", http_port: int = 8000,
          proxy: bool = True):
    """Start serve system actors without deploying (reference:
    serve.start)."""
    controller = _get_or_create_controller()
    if proxy:
        existing_port = ray_tpu.get(controller.get_http_port.remote(),
                                    timeout=30)
        if existing_port is None:
            p = (ray_tpu.remote(ProxyActor)
                 .options(name=PROXY_NAME, lifetime="detached",
                          get_if_exists=True, num_cpus=0.1)
                 .remote(http_host, http_port))
            port = ray_tpu.get(p.ready.remote(), timeout=60)
            ray_tpu.get(controller.set_http_port.remote(port), timeout=30)
    return controller


def run(app: Application, *, name: str = DEFAULT_APP_NAME,
        route_prefix: str = "/", _blocking_ready: bool = True,
        http_port: int = 8000, proxy: bool = True) -> DeploymentHandle:
    """Deploy a bound application; returns the ingress handle."""
    controller = start(http_port=http_port, proxy=proxy)
    specs, ingress = build_specs(app, name, route_prefix)
    ray_tpu.get(controller.deploy_application.remote(name, specs),
                timeout=120)
    h = DeploymentHandle(name, ingress)
    if _blocking_ready:
        _wait_ready(controller, name, timeout=120)
        handle_mod._reset_router()
    return h


def _wait_ready(controller, app_name: str, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = ray_tpu.get(controller.get_status.remote(), timeout=30)
        app_deps = {k: v for k, v in status.items()
                    if k.startswith(app_name + "#")}
        if app_deps and all(v["running_replicas"] >= v["target_replicas"]
                            for v in app_deps.values()):
            return
        time.sleep(0.1)
    raise TimeoutError(f"application {app_name} did not become ready")


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    status = ray_tpu.get(controller.get_status.remote(), timeout=30)
    for key, v in status.items():
        app, dep = key.split("#", 1)
        if app == name and v.get("is_ingress"):
            return DeploymentHandle(app, dep)
    raise ValueError(f"no application named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = DEFAULT_APP_NAME
                          ) -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def status() -> Dict[str, Any]:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return {}
    return ray_tpu.get(controller.get_status.remote(), timeout=30)


def delete(name: str):
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)
    handle_mod._reset_router()


def shutdown():
    """Tear down all serve actors (reference: serve.shutdown)."""
    handle_mod._reset_router()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
    except Exception:
        pass
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
        ray_tpu.get(proxy.shutdown.remote(), timeout=10)
        ray_tpu.kill(proxy)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
