"""Per-request context inside replicas (reference:
python/ray/serve/context.py _serve_request_context)."""

from __future__ import annotations

import contextvars
from dataclasses import dataclass, field


@dataclass
class RequestContext:
    multiplexed_model_id: str = ""
    route: str = ""
    deployment: str = ""
    # Replica-assigned id for this request — correlates replica logs,
    # profiler attribution buckets, and streamed responses.
    request_id: str = ""


_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=RequestContext())


def _get_request_context() -> RequestContext:
    return _request_context.get()


def _set_request_context(ctx: RequestContext):
    _request_context.set(ctx)
