"""Deployment scheduler: where replicas go.

Reference: serve/_private/deployment_scheduler.py — replica scheduling
requests resolved against the cluster (SPREAD by default, compact/PACK
for consolidation) with a ``max_replicas_per_node`` cap. Here the
controller consults ``DeploymentScheduler.choose_node`` before every
replica creation: the choice is pinned with a soft NodeAffinity so the
cluster scheduler still has an escape hatch if the node fills between
decision and placement; ``None`` with eligible=False means "no node can
take a replica right now" and the controller leaves the deployment
under target until the next reconcile tick.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

SPREAD = "SPREAD"
PACK = "PACK"
DEFAULT = "DEFAULT"

_POLICIES = (SPREAD, PACK, DEFAULT)


@dataclass
class PlacementDecision:
    node_id: Optional[str]   # None = let the cluster scheduler pick
    eligible: bool           # False = no node may take a replica now


class DeploymentScheduler:
    def __init__(self, policy: str = SPREAD,
                 max_replicas_per_node: Optional[int] = None):
        if policy not in _POLICIES:
            raise ValueError(
                f"placement_strategy must be one of {_POLICIES}, "
                f"got {policy!r}")
        if max_replicas_per_node is not None and max_replicas_per_node < 1:
            raise ValueError("max_replicas_per_node must be >= 1")
        self.policy = policy
        self.cap = max_replicas_per_node

    def choose_node(self, alive_node_ids: List[str],
                    replicas_per_node: Dict[str, int]
                    ) -> PlacementDecision:
        """Pick a node for one new replica.

        replicas_per_node counts THIS deployment's replicas whose node
        is known; replicas with unknown placement are conservatively
        ignored (they resolve within a reconcile tick or two).
        """
        if not alive_node_ids:
            return PlacementDecision(None, True)
        counts = {n: replicas_per_node.get(n, 0) for n in alive_node_ids}
        eligible = (alive_node_ids if self.cap is None
                    else [n for n in alive_node_ids
                          if counts[n] < self.cap])
        if not eligible:
            return PlacementDecision(None, False)
        if self.policy == DEFAULT and self.cap is None:
            return PlacementDecision(None, True)
        if self.policy == PACK:
            # Fill the busiest eligible node first (consolidation);
            # node-id tie-break keeps decisions deterministic.
            chosen = max(eligible, key=lambda n: (counts[n], n))
        else:  # SPREAD (and capped DEFAULT behaves like SPREAD)
            chosen = min(eligible, key=lambda n: (counts[n], n))
        return PlacementDecision(chosen, True)
