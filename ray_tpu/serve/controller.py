"""ServeController: the control-plane singleton actor.

Reference: python/ray/serve/_private/controller.py:91 and
deployment_state.py — reconciles target deployment state (replica
counts, versions) against live replica actors in a background loop,
autoscales from replica metrics, and serves the routing table to
routers/proxies. Routers poll ``get_routing_snapshot`` guarded by a
version counter — the long-poll host collapsed to versioned pulls.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class DeploymentState:
    def __init__(self, app_name: str, spec: dict, generation: int = 0):
        self.app_name = app_name
        self.spec = spec
        self.name = spec["name"]
        # Per-deploy generation: replica names embed it, so a replica
        # from a deleted/replaced app generation can never be adopted by
        # the next one (recovery reuses the checkpointed generation so
        # adoption of surviving replicas still works).
        self.generation = generation
        self.target_replicas = spec["config"].initial_replicas()
        self.replicas: Dict[str, Any] = {}  # replica_id -> actor handle
        self.replica_started: Dict[str, float] = {}
        self.replica_ready: set = set()
        # replica name -> node_id hex (resolved lazily from the actor
        # table; feeds the deployment scheduler's per-node counts).
        self.replica_node: Dict[str, str] = {}
        # Names whose entry is the scheduler's INTENDED node, not yet
        # confirmed from the actor table (soft affinity can spill).
        self.replica_node_provisional: set = set()
        self.health_fail_counts: Dict[str, int] = {}
        self.pending_requests = 0  # reported by routers on empty table
        self._last_health_check = 0.0
        self._counter = 0
        self._metrics: Dict[str, dict] = {}
        self._last_scale_up = 0.0
        self._last_scale_down = 0.0
        # Router-reported stream TTFT samples: (ts, ttft_sum, count)
        # batches piggybacked on routing-snapshot refreshes, pruned to
        # the autoscaler's look-back window.
        self._stream_stats: List[tuple] = []
        # When the current TTFT/queue-depth breach started (None = no
        # active breach) — upscales require the breach to be SUSTAINED
        # for upscale_delay_s, not a single slow sample.
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        # router id -> last applied cumulative (ttft_sum, ttft_count)
        self._router_cum: Dict[str, tuple] = {}

    def key(self) -> str:
        return f"{self.app_name}#{self.name}"


class ServeController:
    """Async actor; deploy/delete mutate target state, a reconcile loop
    converges the actual state."""

    #: GCS KV namespace for controller state (reference:
    #: serve/_private/application_state.py checkpoints app specs to the
    #: GCS KV so a restarted controller recovers every deployed app).
    KV_NS = "_serve"
    KV_APP_PREFIX = b"serve:app:"

    def __init__(self):
        self.apps: Dict[str, List[str]] = {}  # app -> deployment keys
        self.deployments: Dict[str, DeploymentState] = {}
        self.routing_version = 0
        # Fresh per controller process (NOT checkpointed): routers tag
        # TTFT reports with the last instance id they synced with, so a
        # report whose cumulative totals predate a controller restart is
        # consumed as baseline instead of replayed into the look-back
        # window (recovery reuses deployment generations, so the gen tag
        # alone cannot tell "new router" from "new controller").
        import uuid

        self.instance_id = uuid.uuid4().hex
        self._shutdown = False
        # Serializes check-then-act replica creation: creation awaits
        # off-loop (get_if_exists name lookup), so two interleaved
        # _reconcile_once runs would otherwise both see the same gap
        # and over-create.
        self._reconcile_lock = asyncio.Lock()
        self._recovered = asyncio.get_event_loop().create_task(
            self._recover())
        self._loop_task = asyncio.get_event_loop().create_task(
            self._reconcile_loop())
        self.http_port: Optional[int] = None

    # -- persistence / recovery ----------------------------------------
    # KV calls are blocking control RPCs; from this async actor they
    # must run off-loop (same rule as _kill_async).

    async def _kv(self, fn, *args, **kw):
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(None, lambda: fn(*args, **kw))

    async def _next_generation(self, app_name: str) -> int:
        """Monotonic per-app deploy counter, persisted OUTSIDE the app
        checkpoint (delete must not reset it — a post-delete redeploy
        reusing names would adopt replicas that are mid graceful-stop)."""
        import ray_tpu

        key = b"serve:gen:" + app_name.encode()
        try:
            raw = await self._kv(ray_tpu.kv_get, key,
                                 namespace=self.KV_NS)
            gen = int(raw or 0) + 1
            await self._kv(ray_tpu.kv_put, key, str(gen).encode(),
                           namespace=self.KV_NS)
            return gen
        except Exception:
            logger.exception("generation bump failed; using clock")
            return int(time.time())

    async def _persist_app(self, app_name: str, specs: List[dict],
                           generation: int):
        import cloudpickle

        import ray_tpu

        try:
            await self._kv(ray_tpu.kv_put,
                           self.KV_APP_PREFIX + app_name.encode(),
                           cloudpickle.dumps({"specs": specs,
                                              "gen": generation}),
                           namespace=self.KV_NS)
        except Exception:
            logger.exception("failed to checkpoint app %s", app_name)

    async def _unpersist_app(self, app_name: str):
        import ray_tpu

        try:
            await self._kv(ray_tpu.kv_del,
                           self.KV_APP_PREFIX + app_name.encode(),
                           namespace=self.KV_NS)
        except Exception:
            logger.exception("failed to drop checkpoint of %s", app_name)

    async def _recover(self):
        """Controller restart (including head restart recreating this
        detached actor __init__-fresh): redeploy every checkpointed app.
        Replica creation uses get_if_exists, so replicas that survived a
        controller-only restart are adopted rather than duplicated."""
        import cloudpickle

        import ray_tpu

        try:
            keys = await self._kv(ray_tpu.kv_keys, self.KV_APP_PREFIX,
                                  namespace=self.KV_NS)
        except Exception:
            logger.exception("serve recovery: KV unavailable")
            return
        failed_apps = set()
        for key in keys:
            app_name = key[len(self.KV_APP_PREFIX):].decode()
            try:
                blob = await self._kv(ray_tpu.kv_get, key,
                                      namespace=self.KV_NS)
                if blob is None:
                    continue
                ckpt = cloudpickle.loads(blob)
                await self.deploy_application(
                    app_name, ckpt["specs"], _persist=False,
                    _generation=ckpt.get("gen", 0))
                logger.info("serve recovery: redeployed app %r "
                            "(%d deployments)", app_name,
                            len(ckpt["specs"]))
            except Exception:
                failed_apps.add(app_name)
                logger.exception("serve recovery of app %r failed",
                                 app_name)
        if keys:
            await self._reap_orphan_replicas(failed_apps)

    async def _reap_orphan_replicas(self, failed_apps: set):
        """Pre-crash replicas of earlier generations were recreated as
        detached actors by GCS recovery but belong to no deployment —
        kill them, or they linger serving nothing forever. Replicas of
        apps whose RECOVERY failed are left alone: they may still be
        serving, and killing them would turn a transient recovery error
        into an outage."""
        import ray_tpu

        try:
            named = await self._kv(ray_tpu.list_named_actors, True)
        except Exception:
            return
        known = set()
        for ds in self.deployments.values():
            known.update(ds.replicas)
        for row in named:
            name = row["name"]
            if not name.startswith("SERVE_REPLICA::") or name in known:
                continue
            # name layout: SERVE_REPLICA::<app>#<deployment>#g<gen>#<n>
            app = name[len("SERVE_REPLICA::"):].split("#", 1)[0]
            if app in failed_apps:
                continue
            try:
                actor = await self._kv(
                    ray_tpu.get_actor, name,
                    namespace=row.get("namespace", ""))
            except Exception:
                continue
            logger.info("serve recovery: reaping orphan replica %s",
                        name)
            await _kill_async(actor)

    # -- deploy API -----------------------------------------------------
    async def deploy_application(self, app_name: str,
                                 specs: List[dict],
                                 _persist: bool = True,
                                 _generation: Optional[int] = None
                                 ) -> None:
        if _persist:
            # External deploys wait for recovery: a stale checkpoint
            # being replayed must not stomp a newer deploy.
            try:
                await self._recovered
            except Exception:
                pass
        if _generation is None:
            _generation = await self._next_generation(app_name)
        # Validate/build BEFORE checkpointing — a deploy that raises must
        # not poison the KV with specs every future recovery replays.
        new_states = [DeploymentState(app_name, spec, _generation)
                      for spec in specs]
        for ds in new_states:
            ds.spec["replica_config"].actor_options()  # validates
        if _persist:
            await self._persist_app(app_name, specs, _generation)
        old_keys = set(self.apps.get(app_name, []))
        new_keys = set()
        for ds in new_states:
            key = ds.key()
            new_keys.add(key)
            existing = self.deployments.get(key)
            if existing is not None:
                # Redeploy: replace spec; replicas are replaced by the
                # reconcile loop (fresh generation -> fresh names).
                await self._stop_all_replicas(existing)
            self.deployments[key] = ds
        for stale in old_keys - new_keys:
            st = self.deployments.pop(stale, None)
            if st:
                await self._stop_all_replicas(st)
        self.apps[app_name] = sorted(new_keys)
        await self._reconcile_once()

    async def delete_application(self, app_name: str) -> None:
        await self._unpersist_app(app_name)
        for key in self.apps.pop(app_name, []):
            st = self.deployments.pop(key, None)
            if st:
                await self._stop_all_replicas(st)
        self.routing_version += 1

    async def list_applications(self) -> List[str]:
        return sorted(self.apps)

    async def get_status(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, ds in self.deployments.items():
            out[key] = {
                "target_replicas": ds.target_replicas,
                "running_replicas": len(ds.replica_ready
                                        & set(ds.replicas)),
                "starting_replicas": len(ds.replicas),
                "route_prefix": ds.spec.get("route_prefix"),
                "is_ingress": ds.spec.get("is_ingress", False),
            }
        return out

    async def set_http_port(self, port: int) -> None:
        self.http_port = port

    async def get_http_port(self) -> Optional[int]:
        return self.http_port

    # -- routing table ---------------------------------------------------
    async def get_routing_snapshot(self, stats: Optional[dict] = None
                                   ) -> Dict[str, Any]:
        if stats:
            # Routers batch their locally-observed stream TTFT samples
            # onto the refresh they were already making — the
            # autoscaling signal rides an existing control call instead
            # of a per-request RPC. Totals are cumulative per router:
            # only the delta since that router's last applied report is
            # appended, so a refresh whose reply was lost after we
            # processed it cannot double-count when retried.
            now = time.time()
            rid = stats.pop("_router", None)
            same_controller = (stats.pop("_controller", None)
                               == self.instance_id)
            for key, s in stats.items():
                ds = self.deployments.get(key)
                if ds is None:
                    continue
                cum_sum = float(s.get("ttft_sum", 0.0))
                cum_count = int(s.get("ttft_count", 0))
                rep_gen = s.get("gen")
                if rep_gen is not None and rep_gen != ds.generation:
                    # Samples accrued against a previous generation of
                    # this deployment (the router hasn't refreshed past
                    # a redeploy yet): not this deployment's signal.
                    continue
                if rid is None:
                    d_sum, d_count = cum_sum, cum_count
                elif rid in ds._router_cum:
                    prev_sum, prev_count = ds._router_cum[rid]
                    d_sum = cum_sum - prev_sum
                    d_count = cum_count - prev_count
                    if d_count < 0 or d_sum < 0:
                        # A router's totals are monotonic within one
                        # DeploymentState lifetime, so a negative delta
                        # means this report is STALE (two router threads
                        # can snapshot totals and land out of order).
                        # Drop it and keep the newer stored baseline —
                        # applying the full cumulative total here would
                        # replay the router's entire history into the
                        # look-back window, and regressing the baseline
                        # would double-count the gap on the next report.
                        continue
                elif (rep_gen is not None and same_controller
                        and s.get("first")):
                    # Genuinely-first report from this router: tagged
                    # with OUR generation, OUR controller instance, and
                    # the router's own "never applied before" marker —
                    # the router resets its accumulator when a
                    # deployment's generation changes, so the full
                    # total belongs to this deployment. (Treating it as
                    # baseline would permanently drop any burst fully
                    # contained in one refresh interval.)
                    d_sum, d_count = cum_sum, cum_count
                else:
                    # Unknown router whose totals we can't date: a
                    # gen-less legacy report, one tagged with a previous
                    # controller instance (we restarted and recovery
                    # reused the generation), or a router we evicted
                    # from the bounded _router_cum map (first=False) —
                    # its cumulative history may span hours. Baseline
                    # only: applying the full total would replay that
                    # history into the look-back window and fake an
                    # instant breach.
                    d_sum, d_count = 0.0, 0
                if rid is not None:
                    # Delete-then-insert keeps the dict ordered by most
                    # recent report, so the cap evicts the router that
                    # has gone quietest — not a live long-lived one
                    # (whose eviction would replay its whole cumulative
                    # history as one giant delta).
                    ds._router_cum.pop(rid, None)
                    ds._router_cum[rid] = (cum_sum, cum_count)
                    if len(ds._router_cum) > 256:
                        # Dead routers' ids; two floats each, capped.
                        ds._router_cum.pop(next(iter(ds._router_cum)))
                if d_count > 0:
                    ds._stream_stats.append((now, d_sum, d_count))
                    # _autoscale prunes by look-back window, but only
                    # for deployments WITH an autoscaling config —
                    # bound the list here too so a long-lived streaming
                    # deployment without one can't grow it forever.
                    if len(ds._stream_stats) > 1024:
                        del ds._stream_stats[:-1024]
        table = {}
        for key, ds in self.deployments.items():
            # Route only to replicas that have answered a health check —
            # a starting replica (still importing / warming up jit) would
            # absorb requests its queue can't serve yet.
            ready = sorted(ds.replica_ready & set(ds.replicas))
            cfg = ds.spec["config"]
            table[key] = {
                "replica_names": ready or sorted(ds.replicas),
                "route_prefix": (ds.spec.get("route_prefix")
                                 if ds.spec.get("is_ingress") else None),
                "app": ds.app_name,
                "deployment": ds.name,
                # Routers tag TTFT reports with this and reset their
                # accumulators when it changes, so first reports and
                # redeploys are disambiguated (see stats handling above).
                "gen": ds.generation,
                # Streaming plane: proxies pick response framing and the
                # router picks the backpressure window from here.
                "stream": bool(ds.spec.get("is_generator")),
                "stream_format": getattr(cfg, "stream_format", "auto"),
                "max_queued_stream_chunks": getattr(
                    cfg, "max_queued_stream_chunks", 16),
            }
        return {"version": self.routing_version, "table": table,
                "controller": self.instance_id}

    # -- reconciliation --------------------------------------------------
    async def _reconcile_loop(self):
        while not self._shutdown:
            try:
                await self._reconcile_once()
                await self._autoscale()
                await self._health_check()
            except Exception:
                logger.exception("serve reconcile failed")
            await asyncio.sleep(0.5)

    async def _reconcile_once(self):
        async with self._reconcile_lock:
            await self._reconcile_locked()

    async def _reconcile_locked(self):
        import ray_tpu

        changed = False
        for key, ds in list(self.deployments.items()):
            first_placement = True
            while len(ds.replicas) < ds.target_replicas:
                rid = f"{key}#g{ds.generation}#{ds._counter}"
                ds._counter += 1
                from ray_tpu.serve.replica import Replica

                rc = ds.spec["replica_config"]
                # Deployment scheduler: pick the replica's node (SPREAD/
                # PACK/cap). Blocking actor-table lookups, so off-loop;
                # unknown placements resolve once per deployment per
                # tick (later creations reuse provisional entries — a
                # per-creation resolve would be O(replicas^2) RPCs).
                decision = await asyncio.get_event_loop().run_in_executor(
                    None, self._place_replica, ds, rc, first_placement)
                first_placement = False
                if not decision.eligible:
                    # Every node is at max_replicas_per_node: stay under
                    # target until capacity appears (next reconcile).
                    ds._counter -= 1
                    break
                opts = dict(rc.actor_options())
                if decision.node_id is not None:
                    from ray_tpu.core.task_spec import (
                        NodeAffinitySchedulingStrategy,
                    )

                    # With a max_replicas_per_node cap the affinity is
                    # HARD — soft spillover would silently break the
                    # cap contract on whatever node it lands on (the
                    # replica waits for node capacity instead).
                    # Without a cap, soft: if the node fills between
                    # decision and placement, the cluster scheduler may
                    # still place it elsewhere.
                    opts["scheduling_strategy"] = (
                        NodeAffinitySchedulingStrategy(
                            decision.node_id,
                            soft=rc.max_replicas_per_node is None))
                opts["name"] = f"SERVE_REPLICA::{rid}"
                opts["lifetime"] = "detached"
                # Adoption on controller restart: a replica that
                # survived (controller-only failure) is re-attached by
                # name instead of name-colliding (reference: the
                # controller recovering running replicas from
                # checkpoints). get_if_exists does a BLOCKING name
                # lookup, so creation runs off-loop (same rule as
                # _kill_async).
                opts["get_if_exists"] = True
                spec = ds.spec

                def create(opts=opts, spec=spec, rid=rid,
                           dkey=ds.key()):
                    # App-qualified "app#name", matching the router's
                    # TTFT metrics and the controller's autoscale
                    # events, so one deployment carries ONE tag value
                    # across the whole telemetry plane (and same-named
                    # deployments in two apps never merge series).
                    return ray_tpu.remote(Replica).options(**opts).remote(
                        spec["serialized_callable"],
                        spec.get("init_args", ()),
                        spec.get("init_kwargs", {}),
                        spec["config"].user_config,
                        dkey, rid,
                        # getattr: app checkpoints written before the
                        # engine existed unpickle without the field.
                        getattr(spec["config"], "engine", None),
                    )

                actor = await asyncio.get_event_loop().run_in_executor(
                    None, create)
                name = f"SERVE_REPLICA::{rid}"
                ds.replicas[name] = actor
                ds.replica_started[name] = time.time()
                if decision.node_id is not None:
                    # Provisional: a still-PENDING replica has no actor-
                    # table placement yet, and without this the next
                    # loop iteration would count it as "nowhere" and
                    # stack every new replica on the same node. Soft
                    # affinity makes this the actual node in all but
                    # full-node spillover; the resolver replaces it with
                    # the confirmed node once the replica is placed.
                    ds.replica_node[name] = decision.node_id
                    ds.replica_node_provisional.add(name)
                changed = True
            while len(ds.replicas) > ds.target_replicas:
                name, actor = sorted(ds.replicas.items())[-1]
                del ds.replicas[name]
                ds.replica_started.pop(name, None)
                ds.replica_node.pop(name, None)
                ds.replica_node_provisional.discard(name)
                ds.replica_ready.discard(name)
                asyncio.ensure_future(self._graceful_stop(actor, ds))
                changed = True
        if changed:
            self.routing_version += 1

    async def get_replica_nodes(self, deployment_key: str
                                   ) -> Dict[str, Optional[str]]:
        """Replica name -> node id (resolving unknowns), for tests and
        the status surface."""
        ds = self.deployments.get(deployment_key)
        if ds is None:
            return {}
        await asyncio.get_event_loop().run_in_executor(
            None, self._resolve_replica_nodes, ds)
        return {name: ds.replica_node.get(name) for name in ds.replicas}

    def _resolve_replica_nodes(self, ds: DeploymentState) -> None:
        """Blocking actor-table lookups for replicas whose node is
        unknown (executor thread only)."""
        from ray_tpu import api as _api

        cw = _api._require_worker()
        for name, actor in list(ds.replicas.items()):
            if (name in ds.replica_node
                    and name not in ds.replica_node_provisional):
                continue
            try:
                reply = cw.loop_thread.run(cw.head.call(
                    "get_actor_info",
                    {"actor_id": actor._actor_id.hex()}), timeout=10)
            except Exception:
                continue
            node = reply.get("node_id") if reply.get("found") else None
            if node:
                ds.replica_node[name] = node
                ds.replica_node_provisional.discard(name)

    def _place_replica(self, ds: DeploymentState, rc,
                       resolve: bool = True):
        """Runs in an executor thread (blocking head calls). Resolves
        unknown replica nodes from the actor table, then delegates to
        the DeploymentScheduler (serve/scheduler.py)."""
        import ray_tpu
        from ray_tpu.serve.scheduler import (
            DeploymentScheduler,
            PlacementDecision,
        )

        sched = DeploymentScheduler(rc.placement_strategy,
                                    rc.max_replicas_per_node)
        try:
            nodes = [n["node_id"] for n in ray_tpu.nodes()
                     if n.get("state", "ALIVE") == "ALIVE"]
        except Exception:
            if sched.cap is not None:
                # With a cap, creating blind could silently overload a
                # node past its contract; wait for the next tick.
                return PlacementDecision(None, False)
            nodes = []
        if (not nodes) or (len(nodes) == 1 and sched.cap is None):
            # Single-node (or unknown) cluster with no cap: nothing to
            # decide; skip the actor-table lookups.
            return PlacementDecision(None, True)
        if resolve:
            self._resolve_replica_nodes(ds)
        counts: Dict[str, int] = {}
        for name in ds.replicas:
            node = ds.replica_node.get(name)
            if node:
                counts[node] = counts.get(node, 0) + 1
        return sched.choose_node(nodes, counts)

    async def _graceful_stop(self, actor, ds: DeploymentState):
        try:
            timeout = ds.spec["config"].graceful_shutdown_timeout_s
            # Give the replica most of the budget for its engine drain,
            # keeping a margin for the terminal-fail + user cleanup
            # hook to still run inside OUR wait_for.
            drain = max(1.0, timeout - 2.0)
            await asyncio.wait_for(
                _aref(actor.prepare_shutdown.remote(drain)), timeout)
        except Exception:
            pass
        await _kill_async(actor)

    async def _stop_all_replicas(self, ds: DeploymentState):
        for name, actor in list(ds.replicas.items()):
            asyncio.ensure_future(self._graceful_stop(actor, ds))
        ds.replicas.clear()
        ds.replica_node.clear()
        ds.replica_node_provisional.clear()
        self.routing_version += 1

    async def report_pending_request(self, deployment_key: str) -> None:
        """Routers report a request that found no replicas — the
        scale-from-zero signal (reference: handle-side queued-request
        metrics feeding the autoscaler)."""
        ds = self.deployments.get(deployment_key)
        if ds is not None:
            ds.pending_requests += 1

    def _set_target(self, ds: DeploymentState, new_target: int,
                    direction: str, reason: str, now: float) -> None:
        """The one place replica targets change from autoscaling: every
        decision is observable (counter tagged direction/reason + a
        ``serve/autoscale`` flight-recorder event)."""
        old = ds.target_replicas
        if new_target == old:
            return
        ds.target_replicas = new_target
        if direction == "up":
            ds._last_scale_up = now
        else:
            ds._last_scale_down = now
        from ray_tpu.util import flight_recorder, telemetry

        telemetry.inc("ray_tpu_serve_autoscale_decisions_total", 1,
                      {"deployment": ds.key(), "direction": direction,
                       "reason": reason})
        flight_recorder.record(
            "serve", "autoscale", deployment=ds.key(),
            direction=direction, reason=reason,
            from_replicas=old, to_replicas=new_target)
        logger.info("autoscale %s %s: %d -> %d (%s)", ds.key(),
                    direction, old, new_target, reason)

    async def _autoscale(self):
        now = time.time()
        for key, ds in self.deployments.items():
            cfg = ds.spec["config"].autoscaling_config
            if cfg is None:
                continue
            if not ds.replicas:
                # Scale from zero on queued-request reports.
                if ds.pending_requests > 0 and ds.target_replicas < 1:
                    self._set_target(ds, max(1, cfg.min_replicas),
                                     "up", "pending_requests", now)
                ds.pending_requests = 0
                continue
            ds.pending_requests = 0

            async def grab(actor):
                try:
                    return await asyncio.wait_for(
                        _aref(actor.metrics.remote()), 2.0)
                except Exception:
                    return None

            results = await asyncio.gather(
                *[grab(a) for a in ds.replicas.values()])
            metrics = [m for m in results if m is not None]
            if not metrics:
                continue
            total = sum(m.get("num_ongoing", 0) for m in metrics)
            desired = max(
                cfg.min_replicas,
                min(cfg.max_replicas,
                    -(-total // int(max(1, cfg.target_ongoing_requests)))))

            # --- streaming / engine signals ---------------------------
            engine_ms = [m["engine"] for m in metrics if m.get("engine")]
            # Keyed on CONFIG, not reported metrics: replicas build
            # their engine lazily on the first streamed request, so
            # right after a rolling replace no replica reports engine
            # stats — falling through to the ongoing-based branch then
            # would read num_ongoing=0 as an instant downscale with no
            # sustained-idle requirement. (getattr: app checkpoints
            # written before the engine existed unpickle without it.)
            is_engine = getattr(
                ds.spec["config"], "engine", None) is not None
            look_back = getattr(cfg, "look_back_period_s", 5.0)
            ds._stream_stats = [(t, s, c) for (t, s, c)
                                in ds._stream_stats
                                if now - t <= look_back]
            tcount = sum(c for _, _, c in ds._stream_stats)
            ttft_avg = (sum(s for _, s, _ in ds._stream_stats) / tcount
                        if tcount else None)
            queue_depth = sum(m.get("queue_depth", 0) for m in engine_ms)
            occupancy = sum(m.get("occupancy", 0) for m in engine_ms)
            batch_capacity = sum(m.get("max_batch_size", 0)
                                 for m in engine_ms)

            breach = None
            target_ttft = getattr(cfg, "target_ttft_s", None)
            target_qd = getattr(cfg, "target_queue_depth", None)
            if is_engine and target_ttft is None and target_qd is None:
                # Engine deployments never upscale on num_ongoing (it
                # is pinned by long-lived streams), so an
                # AutoscalingConfig without explicit targets would
                # silently become downscale-only. Default: sustained
                # admission queueing (batch full, requests waiting) is
                # the upscale signal.
                target_qd = 0.0
            if (target_ttft is not None and ttft_avg is not None
                    and ttft_avg > target_ttft):
                breach = "ttft"
            elif (target_qd is not None and engine_ms
                    and queue_depth / len(engine_ms) > target_qd):
                breach = "queue_depth"

            if breach is not None:
                ds._idle_since = None
                if ds._breach_since is None:
                    ds._breach_since = now
                sustained = (now - ds._breach_since
                             >= cfg.upscale_delay_s)
                if (sustained
                        and ds.target_replicas < cfg.max_replicas
                        and now - ds._last_scale_up
                        >= cfg.upscale_delay_s):
                    self._set_target(ds, ds.target_replicas + 1,
                                     "up", breach, now)
                # A breach (even not yet sustained) vetoes downscaling.
                continue
            ds._breach_since = None

            if is_engine:
                # Engine deployments scale UP only on the TTFT /
                # queue-depth breach above and DOWN only on idle
                # occupancy: stream counts sit in num_ongoing for their
                # whole lifetime, so the ongoing-based desired count
                # would misread long-lived healthy streams as demand
                # for more replicas and a full decode batch as idle
                # capacity. (With no engine stats reported yet —
                # lazily-built engines after a replace — occupancy and
                # queue depth read 0, which is at worst a SUSTAINED-idle
                # downscale, never an instant ongoing-based one.)
                occ_frac = occupancy / max(1, batch_capacity)
                idle = (occ_frac
                        <= getattr(cfg, "downscale_occupancy", 0.1)
                        and queue_depth == 0)
                if not idle:
                    ds._idle_since = None
                else:
                    # Idleness must be SUSTAINED for downscale_delay_s —
                    # one instantaneous empty sample between bursts must
                    # not drop a replica and pay the cold-start twice.
                    if ds._idle_since is None:
                        ds._idle_since = now
                    if (now - ds._idle_since >= cfg.downscale_delay_s
                            and ds.target_replicas > cfg.min_replicas
                            and now - ds._last_scale_down
                            >= cfg.downscale_delay_s):
                        self._set_target(ds, ds.target_replicas - 1,
                                         "down", "idle", now)
            elif desired > ds.target_replicas:
                if now - ds._last_scale_up >= cfg.upscale_delay_s:
                    self._set_target(ds, desired, "up", "ongoing", now)
            elif desired < ds.target_replicas:
                if now - ds._last_scale_down >= cfg.downscale_delay_s:
                    self._set_target(ds, max(desired,
                                             ds.target_replicas - 1),
                                     "down", "ongoing", now)

    STARTUP_GRACE_S = 120.0
    CONSECUTIVE_FAILURES_TO_KILL = 3  # reference: replica killed after 3

    async def _health_check(self):
        now = time.time()

        async def check(ds, name, actor):
            try:
                ok = await asyncio.wait_for(
                    _aref(actor.check_health.remote()), 5.0)
            except Exception:
                ok = False
            return ds, name, actor, ok

        probes = []
        for key, ds in self.deployments.items():
            period = ds.spec["config"].health_check_period_s
            if now - ds._last_health_check < period:
                continue
            ds._last_health_check = now
            for name, actor in list(ds.replicas.items()):
                probes.append(check(ds, name, actor))
        if not probes:
            return
        # Probes run concurrently: one blocked replica (sync user code on
        # its loop) must not stall health detection for every deployment.
        for fut in asyncio.as_completed(probes):
            ds, name, actor, ok = await fut
            if name not in ds.replicas:
                continue
            if ok:
                ds.health_fail_counts.pop(name, None)
                if name not in ds.replica_ready:
                    ds.replica_ready.add(name)
                    self.routing_version += 1
                continue
            if name not in ds.replica_ready:
                # Never-ready replica: still starting (worker spawn +
                # imports + warmup jit); only kill past the startup grace.
                age = now - ds.replica_started.get(name, now)
                if age < self.STARTUP_GRACE_S:
                    continue
            else:
                # A ready replica may just be busy with a long sync
                # request; require consecutive failures before killing.
                fails = ds.health_fail_counts.get(name, 0) + 1
                ds.health_fail_counts[name] = fails
                if fails < self.CONSECUTIVE_FAILURES_TO_KILL:
                    continue
            logger.warning("replica %s unhealthy; replacing", name)
            del ds.replicas[name]
            ds.replica_started.pop(name, None)
            ds.replica_node.pop(name, None)
            ds.replica_node_provisional.discard(name)
            ds.replica_ready.discard(name)
            ds.health_fail_counts.pop(name, None)
            await _kill_async(actor)
            self.routing_version += 1

    async def shutdown(self) -> None:
        self._shutdown = True
        for app_name in list(self.apps):
            await self._unpersist_app(app_name)
        for key, ds in list(self.deployments.items()):
            await self._stop_all_replicas(ds)
        self.deployments.clear()
        self.apps.clear()


async def _aref(ref):
    """Await an ObjectRef from inside an async actor (refs are awaitable;
    this wrapper keeps call sites compatible with asyncio.wait_for)."""
    return await ref


async def _kill_async(actor):
    """ray_tpu.kill is a blocking control call; inside an async actor it
    must run off-loop or it deadlocks the actor's own event loop."""
    import ray_tpu

    loop = asyncio.get_event_loop()
    try:
        await loop.run_in_executor(None, lambda: ray_tpu.kill(actor))
    except Exception:
        pass
