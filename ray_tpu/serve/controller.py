"""ServeController: the control-plane singleton actor.

Reference: python/ray/serve/_private/controller.py:91 and
deployment_state.py — reconciles target deployment state (replica
counts, versions) against live replica actors in a background loop,
autoscales from replica metrics, and serves the routing table to
routers/proxies. Routers poll ``get_routing_snapshot`` guarded by a
version counter — the long-poll host collapsed to versioned pulls.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class DeploymentState:
    def __init__(self, app_name: str, spec: dict):
        self.app_name = app_name
        self.spec = spec
        self.name = spec["name"]
        self.target_replicas = spec["config"].initial_replicas()
        self.replicas: Dict[str, Any] = {}  # replica_id -> actor handle
        self.replica_started: Dict[str, float] = {}
        self.replica_ready: set = set()
        self.health_fail_counts: Dict[str, int] = {}
        self.pending_requests = 0  # reported by routers on empty table
        self._last_health_check = 0.0
        self._counter = 0
        self._metrics: Dict[str, dict] = {}
        self._last_scale_up = 0.0
        self._last_scale_down = 0.0

    def key(self) -> str:
        return f"{self.app_name}#{self.name}"


class ServeController:
    """Async actor; deploy/delete mutate target state, a reconcile loop
    converges the actual state."""

    def __init__(self):
        self.apps: Dict[str, List[str]] = {}  # app -> deployment keys
        self.deployments: Dict[str, DeploymentState] = {}
        self.routing_version = 0
        self._shutdown = False
        self._loop_task = asyncio.get_event_loop().create_task(
            self._reconcile_loop())
        self.http_port: Optional[int] = None

    # -- deploy API -----------------------------------------------------
    async def deploy_application(self, app_name: str,
                                 specs: List[dict]) -> None:
        old_keys = set(self.apps.get(app_name, []))
        new_keys = set()
        for spec in specs:
            ds = DeploymentState(app_name, spec)
            key = ds.key()
            new_keys.add(key)
            existing = self.deployments.get(key)
            if existing is not None:
                # Redeploy: replace spec; replicas are replaced by the
                # reconcile loop (version bump -> restart all).
                await self._stop_all_replicas(existing)
                ds._counter = existing._counter
            self.deployments[key] = ds
        for stale in old_keys - new_keys:
            st = self.deployments.pop(stale, None)
            if st:
                await self._stop_all_replicas(st)
        self.apps[app_name] = sorted(new_keys)
        await self._reconcile_once()

    async def delete_application(self, app_name: str) -> None:
        for key in self.apps.pop(app_name, []):
            st = self.deployments.pop(key, None)
            if st:
                await self._stop_all_replicas(st)
        self.routing_version += 1

    async def list_applications(self) -> List[str]:
        return sorted(self.apps)

    async def get_status(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, ds in self.deployments.items():
            out[key] = {
                "target_replicas": ds.target_replicas,
                "running_replicas": len(ds.replica_ready
                                        & set(ds.replicas)),
                "starting_replicas": len(ds.replicas),
                "route_prefix": ds.spec.get("route_prefix"),
                "is_ingress": ds.spec.get("is_ingress", False),
            }
        return out

    async def set_http_port(self, port: int) -> None:
        self.http_port = port

    async def get_http_port(self) -> Optional[int]:
        return self.http_port

    # -- routing table ---------------------------------------------------
    async def get_routing_snapshot(self) -> Dict[str, Any]:
        table = {}
        for key, ds in self.deployments.items():
            # Route only to replicas that have answered a health check —
            # a starting replica (still importing / warming up jit) would
            # absorb requests its queue can't serve yet.
            ready = sorted(ds.replica_ready & set(ds.replicas))
            table[key] = {
                "replica_names": ready or sorted(ds.replicas),
                "route_prefix": (ds.spec.get("route_prefix")
                                 if ds.spec.get("is_ingress") else None),
                "app": ds.app_name,
                "deployment": ds.name,
            }
        return {"version": self.routing_version, "table": table}

    # -- reconciliation --------------------------------------------------
    async def _reconcile_loop(self):
        while not self._shutdown:
            try:
                await self._reconcile_once()
                await self._autoscale()
                await self._health_check()
            except Exception:
                logger.exception("serve reconcile failed")
            await asyncio.sleep(0.5)

    async def _reconcile_once(self):
        import ray_tpu

        changed = False
        for key, ds in list(self.deployments.items()):
            while len(ds.replicas) < ds.target_replicas:
                rid = f"{key}#{ds._counter}"
                ds._counter += 1
                from ray_tpu.serve.replica import Replica

                opts = dict(ds.spec["replica_config"].actor_options())
                opts["name"] = f"SERVE_REPLICA::{rid}"
                opts["lifetime"] = "detached"
                actor = ray_tpu.remote(Replica).options(**opts).remote(
                    ds.spec["serialized_callable"],
                    ds.spec.get("init_args", ()),
                    ds.spec.get("init_kwargs", {}),
                    ds.spec["config"].user_config,
                    ds.name, rid,
                )
                name = f"SERVE_REPLICA::{rid}"
                ds.replicas[name] = actor
                ds.replica_started[name] = time.time()
                changed = True
            while len(ds.replicas) > ds.target_replicas:
                name, actor = sorted(ds.replicas.items())[-1]
                del ds.replicas[name]
                ds.replica_started.pop(name, None)
                ds.replica_ready.discard(name)
                asyncio.ensure_future(self._graceful_stop(actor, ds))
                changed = True
        if changed:
            self.routing_version += 1

    async def _graceful_stop(self, actor, ds: DeploymentState):
        try:
            timeout = ds.spec["config"].graceful_shutdown_timeout_s
            await asyncio.wait_for(
                _aref(actor.prepare_shutdown.remote()), timeout)
        except Exception:
            pass
        await _kill_async(actor)

    async def _stop_all_replicas(self, ds: DeploymentState):
        for name, actor in list(ds.replicas.items()):
            asyncio.ensure_future(self._graceful_stop(actor, ds))
        ds.replicas.clear()
        self.routing_version += 1

    async def report_pending_request(self, deployment_key: str) -> None:
        """Routers report a request that found no replicas — the
        scale-from-zero signal (reference: handle-side queued-request
        metrics feeding the autoscaler)."""
        ds = self.deployments.get(deployment_key)
        if ds is not None:
            ds.pending_requests += 1

    async def _autoscale(self):
        now = time.time()
        for key, ds in self.deployments.items():
            cfg = ds.spec["config"].autoscaling_config
            if cfg is None:
                continue
            if not ds.replicas:
                # Scale from zero on queued-request reports.
                if ds.pending_requests > 0 and ds.target_replicas < 1:
                    ds.target_replicas = max(1, cfg.min_replicas)
                    ds._last_scale_up = now
                ds.pending_requests = 0
                continue
            ds.pending_requests = 0

            async def grab(actor):
                try:
                    m = await asyncio.wait_for(
                        _aref(actor.metrics.remote()), 2.0)
                    return m["num_ongoing"]
                except Exception:
                    return None

            results = await asyncio.gather(
                *[grab(a) for a in ds.replicas.values()])
            ongoing = [r for r in results if r is not None]
            if not ongoing:
                continue
            total = sum(ongoing)
            desired = max(
                cfg.min_replicas,
                min(cfg.max_replicas,
                    -(-total // int(max(1, cfg.target_ongoing_requests)))))
            if desired > ds.target_replicas:
                if now - ds._last_scale_up >= cfg.upscale_delay_s:
                    ds.target_replicas = desired
                    ds._last_scale_up = now
            elif desired < ds.target_replicas:
                if now - ds._last_scale_down >= cfg.downscale_delay_s:
                    ds.target_replicas = max(desired,
                                             ds.target_replicas - 1)
                    ds._last_scale_down = now

    STARTUP_GRACE_S = 120.0
    CONSECUTIVE_FAILURES_TO_KILL = 3  # reference: replica killed after 3

    async def _health_check(self):
        now = time.time()

        async def check(ds, name, actor):
            try:
                ok = await asyncio.wait_for(
                    _aref(actor.check_health.remote()), 5.0)
            except Exception:
                ok = False
            return ds, name, actor, ok

        probes = []
        for key, ds in self.deployments.items():
            period = ds.spec["config"].health_check_period_s
            if now - ds._last_health_check < period:
                continue
            ds._last_health_check = now
            for name, actor in list(ds.replicas.items()):
                probes.append(check(ds, name, actor))
        if not probes:
            return
        # Probes run concurrently: one blocked replica (sync user code on
        # its loop) must not stall health detection for every deployment.
        for fut in asyncio.as_completed(probes):
            ds, name, actor, ok = await fut
            if name not in ds.replicas:
                continue
            if ok:
                ds.health_fail_counts.pop(name, None)
                if name not in ds.replica_ready:
                    ds.replica_ready.add(name)
                    self.routing_version += 1
                continue
            if name not in ds.replica_ready:
                # Never-ready replica: still starting (worker spawn +
                # imports + warmup jit); only kill past the startup grace.
                age = now - ds.replica_started.get(name, now)
                if age < self.STARTUP_GRACE_S:
                    continue
            else:
                # A ready replica may just be busy with a long sync
                # request; require consecutive failures before killing.
                fails = ds.health_fail_counts.get(name, 0) + 1
                ds.health_fail_counts[name] = fails
                if fails < self.CONSECUTIVE_FAILURES_TO_KILL:
                    continue
            logger.warning("replica %s unhealthy; replacing", name)
            del ds.replicas[name]
            ds.replica_started.pop(name, None)
            ds.replica_ready.discard(name)
            ds.health_fail_counts.pop(name, None)
            await _kill_async(actor)
            self.routing_version += 1

    async def shutdown(self) -> None:
        self._shutdown = True
        for key, ds in list(self.deployments.items()):
            await self._stop_all_replicas(ds)
        self.deployments.clear()
        self.apps.clear()


async def _aref(ref):
    """Await an ObjectRef from inside an async actor (refs are awaitable;
    this wrapper keeps call sites compatible with asyncio.wait_for)."""
    return await ref


async def _kill_async(actor):
    """ray_tpu.kill is a blocking control call; inside an async actor it
    must run off-loop or it deadlocks the actor's own event loop."""
    import ray_tpu

    loop = asyncio.get_event_loop()
    try:
        await loop.run_in_executor(None, lambda: ray_tpu.kill(actor))
    except Exception:
        pass
