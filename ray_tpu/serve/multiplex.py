"""@serve.multiplexed: per-replica LRU of loaded models.

Reference: python/ray/serve/multiplex.py (_ModelMultiplexWrapper) — a
replica hosts many models, loading on demand and evicting LRU beyond
max_num_models_per_replica. On TPU the eviction hook matters: dropping
the model reference frees HBM for the next model's weights.
"""

from __future__ import annotations

import asyncio
import collections
import functools
import inspect
from typing import Any, Callable, Optional


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    def wrap(load_fn):
        caches = {}
        locks = {}

        @functools.wraps(load_fn)
        async def wrapper(self, model_id: str) -> Any:
            cache = caches.setdefault(
                id(self), collections.OrderedDict())
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # Per-model load lock: concurrent misses for the same id must
            # not each load a copy of the weights (N× HBM during load).
            lock = locks.setdefault((id(self), model_id), asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = load_fn(self, model_id)
                if inspect.isawaitable(model):
                    model = await model
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    # Drop the reference; HBM-backed arrays free with it.
                    cache.popitem(last=False)
                return model

        wrapper._is_multiplexed = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id from the request context (reference:
    serve.get_multiplexed_model_id). Set by handle.options or the
    'serve_multiplexed_model_id' header through the proxy."""
    from ray_tpu.serve import context

    return context._get_request_context().multiplexed_model_id


