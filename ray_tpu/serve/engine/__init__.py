"""ray_tpu.serve.engine — iteration-level continuous batching for
generator deployments (see ``core.py`` for the engine loop and
``config.py`` for the knobs)."""

from ray_tpu.serve.engine.config import EngineConfig
from ray_tpu.serve.engine.core import (
    ContinuousBatchingEngine,
    EngineOverloadedError,
    EngineRequest,
    Finished,
    SequenceState,
)

__all__ = [
    "ContinuousBatchingEngine",
    "EngineConfig",
    "EngineOverloadedError",
    "EngineRequest",
    "Finished",
    "SequenceState",
]
