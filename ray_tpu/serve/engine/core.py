"""Iteration-level continuous-batching engine (Orca-style).

Reference: the iteration-level scheduling idea from Orca (OSDI '22) as
deployed by vLLM/TGI-class servers — the unit of scheduling is ONE
decode iteration, not one request. ``@serve.batch`` collects requests
for a flush window and then runs the whole batch to completion; a
request that arrives one tick after the flush waits for the entire
batch to drain. This engine instead keeps a per-replica decode loop
running and admits newly-arrived requests into the live batch *between
iterations*, so TTFT under load is bounded by a few decode iterations.

Two user contracts, detected at engine construction:

- **prefill/decode contract** — the deployment callable provides
  ``prefill(batch_state, requests)`` (admit new requests, returns the
  updated batch state) and ``decode_step(batch_state)`` (one iteration;
  returns ``{seq_id: chunk}``, finishing a sequence by returning a
  ``Finished(value)``). An optional ``evict(batch_state, seq_ids)``
  hook is called when sequences leave the batch (finished or
  cancelled) so KV-cache-style slots can be reclaimed. ``decode_step``
  may also accept ``(batch_state, active_seq_ids)`` to see which
  sequences are currently unpaused.
- **auto-wrap** — any generator / async-generator deployment: the
  engine drives one generator per request, advancing every active
  sequence one item per iteration (sync generators advance in a single
  executor hop per iteration so the replica event loop never blocks).

Sequence lifecycle: submitted -> queued (admission queue, bounded by
``max_queued`` with an honest shed) -> admitted (``engine/admitted``
flight event, queue wait observed) -> decoding -> evicted
(``engine/evicted``: finished, cancelled by client disconnect, or
errored). Per-sequence emission is credit-bounded: a slow consumer
pauses ITS sequence (excluded from the next iterations), never the
whole batch.

All engine state is mutated on the replica's event loop only — no
locks. Blocking user code (sync prefill/decode/generators) runs in the
loop's default executor.
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import time
from typing import Any, Dict, List, Optional

from ray_tpu.serve.engine.config import EngineConfig

#: Internal terminal marker on a sequence's output queue.
_DONE = object()


class Finished:
    """Contract-mode sentinel: ``decode_step`` returns ``Finished()``
    (or ``Finished(final_chunk)``) for a sequence that just completed;
    a non-None value is emitted as the sequence's last chunk."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value


class EngineOverloadedError(RuntimeError):
    """Admission queue at ``max_queued``: the request was shed, not
    parked — the honest backpressure signal the autoscaler and clients
    both see."""


@dataclasses.dataclass
class EngineRequest:
    """One admitted request as handed to contract-mode ``prefill``."""

    seq_id: int
    args: tuple
    kwargs: dict


class SequenceState:
    """Per-request decode state tracked by the engine."""

    __slots__ = ("seq_id", "args", "kwargs", "enqueued_at", "admitted_at",
                 "first_chunk_at", "chunks_emitted", "finished",
                 "cancelled", "error", "paused", "out_q", "gen",
                 "gen_is_async")

    def __init__(self, seq_id: int, args: tuple, kwargs: dict):
        self.seq_id = seq_id
        self.args = args
        self.kwargs = kwargs
        self.enqueued_at = time.time()
        self.admitted_at: Optional[float] = None
        self.first_chunk_at: Optional[float] = None
        self.chunks_emitted = 0
        self.finished = False
        self.cancelled = False
        self.error: Optional[BaseException] = None
        self.paused = False
        # Unbounded queue + explicit credit check in _emit: terminal
        # markers must always land even when the consumer is stalled.
        self.out_q: asyncio.Queue = asyncio.Queue()
        self.gen = None            # auto-wrap mode only
        self.gen_is_async = False


def has_engine_contract(callable_: Any) -> bool:
    """Single source of truth for contract-mode detection — used by the
    engine itself AND build_specs' deploy-time gate, so the two cannot
    diverge."""
    return (callable(getattr(callable_, "prefill", None))
            and callable(getattr(callable_, "decode_step", None)))


class ContinuousBatchingEngine:
    """One engine per replica, running as a task on the replica's event
    loop. ``submit()`` parks a request; the loop admits, decodes, and
    fans each iteration's outputs into per-sequence queues that
    ``stream()`` drains into the core streaming lane."""

    def __init__(self, callable_: Any, cfg: EngineConfig,
                 deployment_name: str):
        self.cfg = cfg
        self._deployment = deployment_name
        self._callable = callable_
        if has_engine_contract(callable_):
            prefill = callable_.prefill
            decode = callable_.decode_step
            self._mode = "contract"
            self._prefill_fn = prefill
            self._decode_fn = decode
            self._evict_fn = getattr(callable_, "evict", None)
            params = [
                p for p in inspect.signature(decode).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            self._decode_wants_active = len(params) >= 2
        else:
            if not callable(callable_):
                raise TypeError(
                    f"{deployment_name}: engine deployments need either "
                    "prefill()/decode_step() methods or a generator "
                    "__call__")
            self._mode = "auto"
            self._prefill_fn = self._decode_fn = self._evict_fn = None
            self._decode_wants_active = False
            self._target = callable_
        self._batch_state: Any = None
        self._batch: Dict[int, SequenceState] = {}
        self._admission: asyncio.Queue = asyncio.Queue(
            maxsize=cfg.max_queued)
        self._work = asyncio.Event()
        self._seq_counter = 0
        # Sequences popped from the admission queue but not yet landed
        # in _batch (the await inside _prefill can be cancelled by
        # shutdown); _fail_all covers them so no consumer ever hangs.
        self._admitting: List[SequenceState] = []
        self._stopped = False
        #: True only when the loop died on a bug (not a clean
        #: shutdown) — Replica.check_health reports unhealthy then.
        self.failed = False
        self._draining = False
        # Count of parked-and-cancelled sequences so the per-iteration
        # purge is O(1) when there is nothing to drop.
        self._cancelled_parked = 0
        self.total_admitted = 0
        self.total_evicted = 0
        # Count of SYNC contract hooks currently executing on an
        # executor thread, incremented/decremented INSIDE the thread:
        # wait_for cancels only the awaiting coroutine (and marks the
        # wrapped future done) while the thread keeps running user
        # code, so the future's state can't be trusted — see
        # _sync_call_abandoned.
        self._sync_running = 0
        self._task = asyncio.get_event_loop().create_task(self._run())

    # -- request surface (replica event loop) ---------------------------

    def submit(self, args: tuple, kwargs: dict) -> SequenceState:
        """Park one request on the admission queue; sheds with
        ``EngineOverloadedError`` when ``max_queued`` are already
        parked."""
        if self._stopped or self._draining:
            raise RuntimeError(
                f"{self._deployment}: engine is shut down")
        self._seq_counter += 1
        seq = SequenceState(self._seq_counter, args, kwargs)
        try:
            self._admission.put_nowait(seq)
        except asyncio.QueueFull:
            # Cancelled-while-parked entries must not hold slots
            # against live requests while the batch is full.
            self._purge_cancelled_parked()
            try:
                self._admission.put_nowait(seq)
            except asyncio.QueueFull:
                raise EngineOverloadedError(
                    f"{self._deployment}: engine admission queue full "
                    f"(max_queued={self.cfg.max_queued}); request shed")
        self._update_gauges()
        self._work.set()
        return seq

    async def stream(self, seq: SequenceState):
        """Async generator over one sequence's chunks. Draining below
        the per-sequence window resumes a paused sequence; the caller
        is responsible for ``cancel(seq)`` on early exit."""
        window = self.cfg.max_buffered_chunks_per_seq
        while True:
            item = await seq.out_q.get()
            if seq.paused and seq.out_q.qsize() < window:
                seq.paused = False
                self._work.set()
            if item is _DONE:
                if seq.error is not None:
                    raise seq.error
                return
            yield item

    def cancel(self, seq: SequenceState) -> None:
        """Mark a sequence cancelled (client walked away). Evicted from
        the running batch before the next decode iteration; dropped at
        admission time if still parked in the queue."""
        if seq.finished or seq.cancelled:
            return
        seq.cancelled = True
        if seq.admitted_at is None:
            self._cancelled_parked += 1
        self._work.set()

    def stats(self) -> Dict[str, Any]:
        """Autoscaling signals, polled by the controller through
        ``Replica.metrics()``."""
        return {
            "occupancy": len(self._batch),
            "queue_depth": self._admission.qsize(),
            "max_batch_size": self.cfg.max_batch_size,
            "total_admitted": self.total_admitted,
            "total_evicted": self.total_evicted,
        }

    def begin_drain(self) -> None:
        """Stop admitting NEW requests (submits shed fast) while
        in-flight and already-parked sequences run to completion —
        a routine scale-down or redeploy must not error live streams.
        Pair with ``shutdown()`` to fail whatever is left."""
        self._draining = True
        self._work.set()

    @property
    def idle(self) -> bool:
        return (not self._batch and not self._admitting
                and self._admission.empty())

    async def shutdown(self) -> None:
        self._stopped = True
        self._work.set()
        self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, Exception):  # lint: allow-silent(engine task teardown; sequences are failed terminally below)
            pass
        self._fail_all(
            RuntimeError(f"{self._deployment}: engine shut down"),
            "shutdown")

    def _fail_all(self, err: BaseException, reason: str) -> None:
        """Fail every sequence the engine knows about — in-limbo
        (drained but not yet prefilled), batched, and still parked —
        terminally. Terminal errors, never a hang."""
        for seq in self._admitting:
            self._finish_seq(seq, error=err, reason=reason)
        self._admitting = []
        for seq in list(self._batch.values()):
            self._finish_seq(seq, error=err, reason=reason)
        while True:
            try:
                seq = self._admission.get_nowait()
            except asyncio.QueueEmpty:
                break
            self._finish_seq(seq, error=err, reason=reason)
        self._update_gauges()

    # -- engine loop -----------------------------------------------------

    async def _run(self):
        try:
            while not self._stopped:
                self._work.clear()
                newly = self._drain_admission()
                if newly:
                    self._admitting = newly
                    await self._prefill(newly)
                    self._admitting = []
                self._purge_cancelled_parked()
                await self._evict_cancelled()
                active = [s for s in self._batch.values()
                          if not s.paused and not s.finished]
                self._update_gauges()
                if not active:
                    # Everything finished, paused, or empty: sleep until
                    # a submit / consumer drain / cancel wakes the loop.
                    await self._work.wait()
                    continue
                await self._decode(active)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # An engine bug must surface as terminal errors on every
            # waiting consumer — never a silent hang. The engine stays
            # stopped; new submits fail fast.
            self._stopped = True
            self.failed = True
            self._fail_all(
                RuntimeError(
                    f"{self._deployment}: engine loop failed: {e!r}"),
                "error")
            raise

    def _purge_cancelled_parked(self) -> None:
        """Drop cancelled entries still parked in the admission queue so
        they stop counting toward ``max_queued`` / the queue-depth gauge
        even while the batch is full (``_drain_admission`` can't pop
        then). Runs entirely on the event loop, so the drain/re-put is
        not interleaved with submits."""
        if not self._cancelled_parked:
            return  # O(1) on the hot path when nothing was cancelled
        keep: List[SequenceState] = []
        purged = False
        while True:
            try:
                seq = self._admission.get_nowait()
            except asyncio.QueueEmpty:
                break
            if seq.cancelled:
                self._finish_seq(seq, reason="cancelled")
                purged = True
            else:
                keep.append(seq)
        for seq in keep:
            self._admission.put_nowait(seq)
        self._cancelled_parked = 0
        if purged:
            self._update_gauges()

    def _drain_admission(self) -> List[SequenceState]:
        """Admit parked requests up to the free batch capacity.
        Requests cancelled while parked are dropped HERE — never
        decoded for a dead client."""
        out: List[SequenceState] = []
        while len(self._batch) + len(out) < self.cfg.max_batch_size:
            try:
                seq = self._admission.get_nowait()
            except asyncio.QueueEmpty:
                break
            if seq.cancelled:
                self._cancelled_parked = max(
                    0, self._cancelled_parked - 1)
                self._finish_seq(seq, reason="cancelled")
                continue
            out.append(seq)
        return out

    async def _prefill(self, newly: List[SequenceState]):
        from ray_tpu.util import flight_recorder, telemetry

        now = time.time()
        for seq in newly:
            seq.admitted_at = now
            self.total_admitted += 1
            telemetry.observe(
                "ray_tpu_serve_engine_queue_wait_seconds",
                max(0.0, now - seq.enqueued_at),
                {"deployment": self._deployment})
            flight_recorder.record(
                "engine", "admitted", deployment=self._deployment,
                seq=seq.seq_id,
                queue_wait_ms=round((now - seq.enqueued_at) * 1e3, 3),
                batch=len(self._batch))
        if self._mode == "contract":
            reqs = [EngineRequest(s.seq_id, s.args, s.kwargs)
                    for s in newly]
            try:
                self._batch_state = await self._bounded(self._call_user(
                    self._prefill_fn, self._batch_state, reqs))
            except Exception as e:
                if self._sync_call_abandoned():
                    raise self._wedged_error(e) from e
                for seq in newly:
                    self._finish_seq(seq, error=e, reason="error")
                if self._evict_fn is not None:
                    # A partially-run prefill may have allocated
                    # batch_state slots (KV cache) for the new seq_ids
                    # before failing; route them through the user's
                    # evict hook so repeated prefill failures cannot
                    # leak batch capacity.
                    await self._call_evict([s.seq_id for s in newly])
                return
            for seq in newly:
                self._batch[seq.seq_id] = seq
            return
        for seq in newly:
            try:
                gen = self._target(*seq.args, **seq.kwargs)
                if inspect.isawaitable(gen):
                    gen = await self._bounded(gen)
                if inspect.isasyncgen(gen):
                    seq.gen, seq.gen_is_async = gen, True
                elif hasattr(gen, "__next__"):
                    seq.gen, seq.gen_is_async = gen, False
                else:
                    raise TypeError(
                        f"{self._deployment}: engine deployment "
                        "callable returned "
                        f"{type(gen).__name__}, not a generator/async "
                        "generator (add prefill/decode_step for the "
                        "batched contract)")
            except Exception as e:
                self._finish_seq(seq, error=e, reason="error")
                continue
            self._batch[seq.seq_id] = seq

    async def _evict_cancelled(self):
        cancelled = [s for s in self._batch.values()
                     if s.cancelled and not s.finished]
        if not cancelled:
            return
        for seq in cancelled:
            if seq.gen is not None:
                try:
                    if seq.gen_is_async:
                        # Bounded: a finally-block awaiting a hung
                        # upstream must not wedge the engine loop.
                        await self._bounded(seq.gen.aclose())
                    else:
                        seq.gen.close()
                except Exception:  # lint: allow-silent(user generator cleanup on a cancelled sequence; the sequence is already terminal)
                    pass
            self._finish_seq(seq, reason="cancelled")
        if self._mode == "contract" and self._evict_fn is not None:
            await self._call_evict([s.seq_id for s in cancelled])

    async def _call_evict(self, seq_ids: List[int]):
        try:
            out = await self._bounded(self._call_user(
                self._evict_fn, self._batch_state, seq_ids))
            if out is not None:
                self._batch_state = out
        except Exception as e:
            if self._sync_call_abandoned():
                raise self._wedged_error(e) from e
            from ray_tpu.util import flight_recorder

            flight_recorder.swallow("serve.engine_evict_hook", e)

    async def _decode(self, active: List[SequenceState]):
        if self._mode == "contract":
            await self._decode_contract(active)
        else:
            await self._decode_auto(active)

    async def _decode_contract(self, active: List[SequenceState]):
        try:
            if self._decode_wants_active:
                out = await self._bounded(self._call_user(
                    self._decode_fn, self._batch_state,
                    [s.seq_id for s in active]))
            else:
                out = await self._bounded(self._call_user(
                    self._decode_fn, self._batch_state))
            # Normalize inside the try: a malformed return value is a
            # user error like a raising decode_step — it must not
            # escape to the loop's crash handler and brick the engine.
            if out is not None and not hasattr(out, "items"):
                raise TypeError(
                    f"{self._deployment}: decode_step must return a "
                    "mapping of seq_id -> chunk (or Finished), got "
                    f"{type(out).__name__}")
            items = list(out.items()) if out else []
        except Exception as e:
            if self._sync_call_abandoned():
                raise self._wedged_error(e) from e
            # A failing decode_step poisons the whole batch state: fail
            # every in-flight sequence terminally (honest errors beat a
            # wedged batch) and start fresh for future admissions.
            for seq in list(self._batch.values()):
                self._finish_seq(seq, error=e, reason="error")
            self._batch_state = None
            return
        finished_ids: List[int] = []
        progressed = False
        for sid, chunk in items:
            seq = self._batch.get(sid)
            if seq is None or seq.finished:
                continue
            progressed = True
            if isinstance(chunk, Finished):
                if chunk.value is not None:
                    self._emit(seq, chunk.value)
                finished_ids.append(sid)
                self._finish_seq(seq)
            else:
                self._emit(seq, chunk)
                if seq.finished:
                    # _emit hard-capped a stalled consumer: route the
                    # eviction through the user's evict hook too, so
                    # its batch_state slot (KV cache) is reclaimed and
                    # decode_step stops computing for a dead seq_id.
                    finished_ids.append(sid)
        if finished_ids and self._evict_fn is not None:
            await self._call_evict(finished_ids)
        if not progressed:
            await asyncio.sleep(self.cfg.empty_step_sleep_s)

    async def _decode_auto(self, active: List[SequenceState]):
        sync_seqs = [s for s in active if not s.gen_is_async]
        async_seqs = [s for s in active if s.gen_is_async]
        # Overlap the sync-generator executor hop with the async
        # advances: a mixed batch's iteration latency is the max of the
        # two, not the sum.
        groups = []
        if sync_seqs:
            loop = asyncio.get_event_loop()
            groups.append(loop.run_in_executor(
                None, _advance_sync, sync_seqs))
        if async_seqs:
            async def advance_bounded(s):
                try:
                    return await self._bounded(_advance_async(s))
                except asyncio.TimeoutError:
                    return (s, "error", RuntimeError(
                        f"{self._deployment}: seq {s.seq_id} decode "
                        "iteration exceeded "
                        f"{self.cfg.decode_iteration_timeout_s}s "
                        "(decode_iteration_timeout_s); evicted so the "
                        "rest of the batch keeps decoding"))

            groups.append(asyncio.gather(
                *[advance_bounded(s) for s in async_seqs]))
        results: List[tuple] = []
        for group in await asyncio.gather(*groups):
            results.extend(group)
        for seq, kind, val in results:
            if kind == "chunk":
                self._emit(seq, val)
            elif kind == "done":
                self._finish_seq(seq)
            else:
                self._finish_seq(seq, error=val, reason="error")

    # -- helpers ---------------------------------------------------------

    async def _bounded(self, awaitable):
        """Apply ``decode_iteration_timeout_s`` to one engine await so a
        hung user coroutine fails terminally instead of wedging the
        batch and admission forever."""
        t = self.cfg.decode_iteration_timeout_s
        if not t:
            return await awaitable
        return await asyncio.wait_for(awaitable, t)

    async def _call_user(self, fn, *args):
        """Run one user hook without ever blocking the replica event
        loop: coroutine functions are awaited in place, sync functions
        (jit'd model steps, KV-cache bookkeeping) hop to the default
        executor."""
        if inspect.iscoroutinefunction(fn):
            return await fn(*args)
        loop = asyncio.get_event_loop()

        def _invoke():
            self._sync_running += 1
            try:
                return fn(*args)
            finally:
                self._sync_running -= 1

        out = await loop.run_in_executor(None, _invoke)
        if inspect.isawaitable(out):
            out = await out
        return out

    def _sync_call_abandoned(self) -> bool:
        """True when a timed-out SYNC user hook's executor thread is
        still running user code. Issuing another user call then would
        race two unsynchronized threads over the same user object /
        batch state — the engine must stop instead (terminal errors on
        every sequence; check_health turns unhealthy so the controller
        replaces the replica). Only meaningful from the engine loop's
        exception paths, where any legitimate call has already
        completed."""
        return self._sync_running > 0

    def _wedged_error(self, e: BaseException) -> RuntimeError:
        return RuntimeError(
            f"{self._deployment}: a sync prefill/decode_step/evict "
            "exceeded decode_iteration_timeout_s but its executor "
            "thread is still running user code; stopping the engine "
            f"rather than racing a second call against it ({e!r})")

    def _emit(self, seq: SequenceState, chunk: Any):
        if seq.first_chunk_at is None:
            seq.first_chunk_at = time.time()
        seq.chunks_emitted += 1
        seq.out_q.put_nowait(chunk)
        qsize = seq.out_q.qsize()
        if qsize >= self.cfg.max_buffered_chunks_per_seq:
            # Credit exhausted: pause THIS sequence's decoding until its
            # consumer drains below the window — the batch keeps going.
            seq.paused = True
        if qsize >= 4 * self.cfg.max_buffered_chunks_per_seq:
            # A paused sequence can still be produced for when the
            # contract's decode_step doesn't accept active_seq_ids (the
            # engine can't stop production for one sequence then). Cap
            # the buffer honestly rather than let one stalled consumer
            # grow out_q until the replica OOMs.
            self._finish_seq(seq, error=RuntimeError(
                f"{self._deployment}: seq {seq.seq_id} evicted — "
                f"consumer stalled with {qsize} chunks buffered (window "
                f"{self.cfg.max_buffered_chunks_per_seq}); accept "
                "active_seq_ids in decode_step to pause slow sequences "
                "instead"), reason="backpressure")

    def _finish_seq(self, seq: SequenceState,
                    error: Optional[BaseException] = None,
                    reason: str = "finished"):
        if seq.finished:
            return
        from ray_tpu.util import flight_recorder

        seq.finished = True
        seq.error = error
        self._batch.pop(seq.seq_id, None)
        self.total_evicted += 1
        seq.out_q.put_nowait(_DONE)
        flight_recorder.record(
            "engine", "evicted",
            severity=("info" if reason == "finished" else "warn"),
            deployment=self._deployment, seq=seq.seq_id, reason=reason,
            chunks=seq.chunks_emitted)

    def _update_gauges(self):
        from ray_tpu.util import telemetry

        tags = {"deployment": self._deployment,
                "proc": telemetry.proc_tag()}
        telemetry.set_gauge("ray_tpu_serve_engine_batch_occupancy",
                            len(self._batch), tags)
        telemetry.set_gauge("ray_tpu_serve_engine_queue_depth",
                            self._admission.qsize(), tags)


def _advance_sync(seqs: List[SequenceState]) -> List[tuple]:
    """(executor thread) Advance each sync generator one item.
    StopIteration must not cross the executor boundary — it is folded
    into the result tuples here."""
    out = []
    for s in seqs:
        try:
            out.append((s, "chunk", next(s.gen)))
        except StopIteration:
            out.append((s, "done", None))
        except Exception as e:  # noqa: BLE001 — becomes the seq's terminal error
            out.append((s, "error", e))
    return out


async def _advance_async(s: SequenceState) -> tuple:
    try:
        return (s, "chunk", await s.gen.__anext__())
    except StopAsyncIteration:
        return (s, "done", None)
    except Exception as e:  # noqa: BLE001 — becomes the seq's terminal error
        return (s, "error", e)
