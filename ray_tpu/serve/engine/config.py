"""EngineConfig: the continuous-batching engine's per-deployment knobs.

A plain dataclass (like serve/config.py's schemas) so it pickles through
the controller's app checkpoint and the replica actor's creation args.
Kept dependency-free: serve/config.py imports this module, so it must
not import anything from ``ray_tpu.serve``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class EngineConfig:
    """Opt a deployment into iteration-level continuous batching
    (``@serve.deployment(engine=EngineConfig(...))``).

    The engine admits newly-arrived requests into the running batch
    *between decode iterations* — there is no flush window, so a request
    arriving mid-decode waits a few iterations for its first token, not
    the residual decode time of the in-flight batch.
    """

    #: Max sequences decoded together in one iteration. New requests are
    #: admitted whenever the batch is below this, even mid-decode.
    max_batch_size: int = 8
    #: Admission queue bound. A request arriving while ``max_queued``
    #: requests are already parked is shed with an honest
    #: ``EngineOverloadedError`` instead of growing an unbounded queue.
    max_queued: int = 128
    #: Per-sequence emission credit: chunks a sequence may have emitted
    #: but its consumer not yet taken before the engine pauses THAT
    #: sequence (the rest of the batch keeps decoding). Resumed the
    #: moment the consumer drains below the window. Pausing requires the
    #: engine to be able to skip the sequence — auto-wrapped generators
    #: and contract ``decode_step(batch_state, active_seq_ids)`` both
    #: can; a contract ``decode_step(batch_state)`` that ignores
    #: ``active_seq_ids`` keeps producing for paused sequences, so the
    #: engine buffers up to 4x this window and then evicts the stalled
    #: sequence with a terminal error rather than grow the buffer until
    #: the replica OOMs.
    max_buffered_chunks_per_seq: int = 8
    #: Sleep applied when a decode iteration makes no progress (a
    #: contract-mode ``decode_step`` returning nothing) so a stalled
    #: model can't hot-spin the replica's event loop.
    empty_step_sleep_s: float = 0.002
    #: Bound on one decode iteration's awaits (per-sequence async
    #: generator advance; contract-mode prefill/decode_step call). A
    #: sequence or batch step exceeding it is failed terminally instead
    #: of wedging the whole engine — without this, one generator
    #: awaiting a hung upstream freezes every other sequence AND
    #: admission, while check_health keeps passing. 0 disables. A
    #: blocked *sync* generator cannot be interrupted (its executor
    #: thread is stuck in user code) and is not covered. A timed-out
    #: *sync* contract hook stops the WHOLE engine (terminal errors on
    #: every sequence, replica reported unhealthy and replaced): its
    #: executor thread is still running user code, and issuing another
    #: prefill/decode_step would race two threads over the same batch
    #: state.
    decode_iteration_timeout_s: float = 60.0

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.max_queued < 1:
            raise ValueError("max_queued must be >= 1")
        if self.max_buffered_chunks_per_seq < 1:
            raise ValueError("max_buffered_chunks_per_seq must be >= 1")
        if self.empty_step_sleep_s < 0:
            raise ValueError("empty_step_sleep_s must be >= 0")
        if self.decode_iteration_timeout_s < 0:
            raise ValueError("decode_iteration_timeout_s must be >= 0")
