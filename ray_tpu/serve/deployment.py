"""Deployment: declarative unit of serving.

Reference: python/ray/serve/deployment.py + api.py (@serve.deployment).
``Deployment.bind(*args)`` produces an Application node; bound arguments
that are themselves Applications are replaced with DeploymentHandles at
deploy time (the reference's DAG build in build_app).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

from ray_tpu.core import serialization as _ser

from ray_tpu.serve.config import (
    AutoscalingConfig,
    DeploymentConfig,
    ReplicaConfig,
)
from ray_tpu.serve.engine.config import EngineConfig


class Application:
    """A bound deployment DAG node (reference: serve Application)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _collect(self, out: Dict[str, "Application"]):
        name = self.deployment.name
        existing = out.get(name)
        if existing is not None and existing is not self:
            raise ValueError(f"duplicate deployment name {name!r}")
        out[name] = self
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, Application):
                a._collect(out)


class Deployment:
    def __init__(self, func_or_class, name: str,
                 config: DeploymentConfig,
                 replica_config: ReplicaConfig,
                 route_prefix: Optional[str] = None):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config
        self.replica_config = replica_config
        self.route_prefix = route_prefix

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                user_config: Optional[dict] = None,
                autoscaling_config: Optional[
                    Union[AutoscalingConfig, dict]] = None,
                num_cpus: Optional[float] = None,
                num_tpus: Optional[float] = None,
                resources: Optional[Dict[str, float]] = None,
                placement_strategy: Optional[str] = None,
                max_replicas_per_node: Optional[int] = None,
                max_queued_stream_chunks: Optional[int] = None,
                stream_format: Optional[str] = None,
                engine: Optional[Union[EngineConfig, dict]] = None,
                route_prefix: Optional[str] = None) -> "Deployment":
        cfg = DeploymentConfig(
            num_replicas=(num_replicas if num_replicas is not None
                          else self.config.num_replicas),
            max_ongoing_requests=(max_ongoing_requests
                                  if max_ongoing_requests is not None
                                  else self.config.max_ongoing_requests),
            user_config=(user_config if user_config is not None
                         else self.config.user_config),
            autoscaling_config=_coerce_autoscaling(
                autoscaling_config, self.config.autoscaling_config),
            max_queued_stream_chunks=(
                max_queued_stream_chunks
                if max_queued_stream_chunks is not None
                else self.config.max_queued_stream_chunks),
            stream_format=(stream_format if stream_format is not None
                           else self.config.stream_format),
            engine=_coerce_engine(
                engine if engine is not None else self.config.engine),
        )
        rc = ReplicaConfig(
            num_cpus=(num_cpus if num_cpus is not None
                      else self.replica_config.num_cpus),
            num_tpus=(num_tpus if num_tpus is not None
                      else self.replica_config.num_tpus),
            resources=(resources if resources is not None
                       else self.replica_config.resources),
            placement_strategy=(
                placement_strategy if placement_strategy is not None
                else self.replica_config.placement_strategy),
            max_replicas_per_node=(
                max_replicas_per_node
                if max_replicas_per_node is not None
                else self.replica_config.max_replicas_per_node),
        )
        return Deployment(
            self.func_or_class,
            name or self.name,
            cfg, rc,
            route_prefix if route_prefix is not None else self.route_prefix,
        )


def _coerce_autoscaling(value, default):
    if value is None:
        return default
    if isinstance(value, dict):
        return AutoscalingConfig(**value)
    return value


def _coerce_engine(value):
    if isinstance(value, dict):
        return EngineConfig(**value)
    return value


def deployment(func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 100,
               user_config: Optional[dict] = None,
               autoscaling_config=None,
               num_cpus: float = 1.0, num_tpus: float = 0.0,
               resources: Optional[Dict[str, float]] = None,
               placement_strategy: str = "SPREAD",
               max_replicas_per_node: Optional[int] = None,
               max_queued_stream_chunks: int = 16,
               stream_format: str = "auto",
               engine: Optional[Union[EngineConfig, dict]] = None,
               route_prefix: Optional[str] = None):
    """@serve.deployment decorator (reference: serve/api.py:deployment)."""

    def wrap(fc):
        return Deployment(
            fc,
            name or fc.__name__,
            DeploymentConfig(
                num_replicas=num_replicas,
                max_ongoing_requests=max_ongoing_requests,
                user_config=user_config,
                autoscaling_config=_coerce_autoscaling(
                    autoscaling_config, None),
                max_queued_stream_chunks=max_queued_stream_chunks,
                stream_format=stream_format,
                engine=_coerce_engine(engine),
            ),
            ReplicaConfig(num_cpus=num_cpus, num_tpus=num_tpus,
                          resources=resources,
                          placement_strategy=placement_strategy,
                          max_replicas_per_node=max_replicas_per_node),
            route_prefix,
        )

    if func_or_class is not None:
        return wrap(func_or_class)
    return wrap


def build_specs(app: Application, app_name: str,
                default_route_prefix: str) -> Tuple[List[dict], str]:
    """Flatten a bound DAG into controller deploy specs; nested bound
    nodes become DeploymentHandles (reference: build_app)."""
    from ray_tpu.serve.handle import DeploymentHandle

    nodes: Dict[str, Application] = {}
    app._collect(nodes)
    ingress_name = app.deployment.name

    def resolve(v):
        if isinstance(v, Application):
            return DeploymentHandle(app_name, v.deployment.name)
        return v

    specs = []
    for name, node in nodes.items():
        d = node.deployment
        is_ingress = name == ingress_name
        route = d.route_prefix
        if is_ingress and route is None:
            route = default_route_prefix
        if (d.config.engine is not None
                and not _callable_is_generator(d.func_or_class)
                and not _has_engine_contract(d.func_or_class)):
            raise TypeError(
                f"deployment '{name}': engine=EngineConfig(...) needs "
                "a generator/async-generator __call__ or the "
                "prefill/decode_step contract — rejecting at deploy "
                "time (every request would fail at first traffic "
                "otherwise)")
        specs.append({
            "name": name,
            "serialized_callable": _ser.dumps_control(d.func_or_class),
            "init_args": tuple(resolve(a) for a in node.args),
            "init_kwargs": {k: resolve(v) for k, v in node.kwargs.items()},
            "config": d.config,
            "replica_config": d.replica_config,
            "route_prefix": route if is_ingress else None,
            "is_ingress": is_ingress,
            # Generator deployments stream by default through the proxy
            # (the replica still enforces this at execution time — the
            # flag only picks the proxy's response mode up front).
            # Engine deployments always stream: the continuous-batching
            # loop emits per-sequence chunks even when the user supplies
            # the prefill/decode contract instead of a generator.
            "is_generator": (_callable_is_generator(d.func_or_class)
                             or d.config.engine is not None),
        })
    return specs, ingress_name


def _has_engine_contract(func_or_class) -> bool:
    from ray_tpu.serve.engine.core import has_engine_contract

    return has_engine_contract(func_or_class)


def _callable_is_generator(func_or_class) -> bool:
    """Does this deployment's ``__call__`` produce a stream? (The proxy
    must choose chunked/SSE framing before the first chunk exists.)"""
    import inspect

    target = func_or_class
    if inspect.isclass(func_or_class):
        target = getattr(func_or_class, "__call__", None)
        if target is None:
            return False
    return (inspect.isgeneratorfunction(target)
            or inspect.isasyncgenfunction(target))
