"""Router: replica choice for a deployment.

Reference: python/ray/serve/_private/router.py:287 +
replica_scheduler/pow_2_scheduler.py:51 — pick two random replicas,
route to the one with fewer ongoing requests. Queue lengths are tracked
router-locally (incremented on send, decremented on completion), the
same local-information design as the reference; the routing table is
refreshed from the controller when its version moves.

Robustness: assignment runs under the unified ``RetryPolicy``
(core/retry.py) instead of a hand-rolled attempt loop, and a
per-replica ``CircuitBreaker`` sheds traffic away from replicas whose
sends keep failing while they back off (reference: the replica
scheduler's blocklisting of unhealthy replicas). All timeouts come
from ``core/config.py`` (``RAY_TPU_SERVE_*`` env overridable).
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.core.config import get_config
from ray_tpu.core.retry import CircuitBreaker, RetryPolicy
from ray_tpu.util import telemetry, tracing


class Router:
    def __init__(self, controller_handle, refresh_period_s: float = 1.0):
        self._controller = controller_handle
        self._refresh_period = refresh_period_s
        # Reentrant: stream done-callbacks can fire from GC
        # (ObjectRefGenerator.__del__ -> close -> _fire_terminal) on a
        # thread that is already inside a locked router section; a
        # plain Lock would self-deadlock there.
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock("serve.Router._lock", reentrant=True)
        self._version = -1
        self._last_refresh = 0.0
        # Locally-observed stream TTFT samples (deployment key ->
        # [sum_seconds, count]), reported CUMULATIVELY (never cleared)
        # with the next routing-snapshot refresh — the autoscaler's
        # TTFT signal. Cumulative totals + the router id make the
        # piggyback idempotent: the controller appends only the delta
        # since this router's last applied report, so a reply lost
        # after the controller processed it can neither drop nor
        # double-count samples.
        import uuid

        self._router_id = uuid.uuid4().hex
        self._ttft_acc: Dict[str, list] = {}
        # deployment key -> generation the accumulator belongs to; reset
        # on redeploy so old-generation samples never pollute the new
        # deployment's autoscaling signal.
        self._ttft_gen: Dict[str, Any] = {}
        # Last controller instance id seen; echoed on reports so a
        # restarted controller treats our pre-restart cumulative totals
        # as baseline instead of replaying them as fresh samples.
        self._ctrl_instance: Optional[str] = None
        # Deployment keys whose totals this controller instance has
        # already applied a report for. First reports carry first=True,
        # which is the ONLY case the controller may apply the full
        # cumulative total — a router evicted from the controller's
        # bounded per-router baseline map reports first=False and is
        # re-baselined instead of replaying its history.
        self._reported_keys: set = set()
        # deployment key -> list of replica actor names
        self._table: Dict[str, dict] = {}
        self._handles: Dict[str, Any] = {}  # replica name -> actor handle
        self._qlen: Dict[str, int] = {}
        cfg = get_config()
        self._control_timeout = cfg.serve_control_timeout_s
        self._scale_wait_timeout = cfg.serve_scale_wait_timeout_s
        # Assignment envelope: every failure mode inside one attempt
        # (dead replica handle, no-replica window) is generic, so retry
        # on any exception — the attempt count and backoff still come
        # from the shared config knobs.
        self._assign_policy = RetryPolicy.from_config(
            cfg, max_attempts=max(1, cfg.serve_assign_max_attempts),
            retry_on=(Exception,))
        self._breaker = CircuitBreaker(
            failure_threshold=cfg.serve_cb_failure_threshold,
            reset_timeout_s=cfg.serve_cb_reset_timeout_s)

    def _note_ttft(self, deployment_key: str, ttft_s: float) -> None:
        with self._lock:
            acc = self._ttft_acc.setdefault(deployment_key, [0.0, 0])
            acc[0] += ttft_s
            acc[1] += 1

    def _refresh(self, force: bool = False):
        now = time.time()
        if not force and now - self._last_refresh < self._refresh_period:
            return
        with self._lock:
            stats = {k: {"ttft_sum": v[0], "ttft_count": v[1],
                         "gen": self._ttft_gen.get(k),
                         "first": k not in self._reported_keys}
                     for k, v in self._ttft_acc.items() if v[1]}
        reported_keys = set(stats)
        if stats:
            stats["_router"] = self._router_id
            stats["_controller"] = self._ctrl_instance
        # A failed refresh loses nothing: the totals are cumulative, so
        # the next successful one carries every sample accrued since
        # the controller's last applied report.
        snap = ray_tpu.get(
            self._controller.get_routing_snapshot.remote(stats or None),
            timeout=self._control_timeout)
        with self._lock:
            self._last_refresh = now
            # Read OUTSIDE the version check: a recovered controller can
            # come back at the same routing version.
            new_ctrl = snap.get("controller")
            if new_ctrl != self._ctrl_instance:
                # New controller instance: only the keys in THIS report
                # have a baseline there (applied via the stale-nonce
                # path); everything else is first again.
                self._reported_keys = reported_keys
            else:
                self._reported_keys |= reported_keys
            self._ctrl_instance = new_ctrl
            if snap["version"] != self._version:
                self._version = snap["version"]
                self._table = snap["table"]
                live = {n for e in self._table.values()
                        for n in e["replica_names"]}
                # Sync the breaker to the live set (not to _handles —
                # the assign failure path pops handles first, which
                # would leak those replicas' breaker entries forever).
                self._breaker.retain(live)
                self._handles = {n: h for n, h in self._handles.items()
                                 if n in live}
                self._qlen = {n: q for n, q in self._qlen.items()
                              if n in live}
                # The cumulative TTFT accumulator is never drained —
                # drop deleted deployments' keys so it tracks the
                # routing table instead of growing forever, and reset
                # it on a generation change (redeploy): the controller
                # applies a first report tagged with the current
                # generation in FULL, so the totals must contain only
                # this generation's samples.
                for k, entry in self._table.items():
                    g = entry.get("gen")
                    if self._ttft_gen.get(k) != g:
                        self._ttft_gen[k] = g
                        self._ttft_acc.pop(k, None)
                        # The redeployed DeploymentState starts with an
                        # empty baseline map: our next report for this
                        # key is a FIRST report again, or the controller
                        # would baseline away the first post-redeploy
                        # refresh interval of samples.
                        self._reported_keys.discard(k)
                self._ttft_acc = {k: v for k, v in self._ttft_acc.items()
                                  if k in self._table}
                self._ttft_gen = {k: v for k, v in self._ttft_gen.items()
                                  if k in self._table}
                self._reported_keys &= set(self._table)

    def route_for_prefix(self, path: str) -> Optional[str]:
        """Longest-prefix route match (proxy use)."""
        self._refresh()
        best_key, best_len = None, -1
        for key, entry in self._table.items():
            rp = entry.get("route_prefix")
            if rp is None:
                continue
            if (path == rp or path.startswith(rp.rstrip("/") + "/")
                    or rp == "/"):
                if len(rp) > best_len:
                    best_key, best_len = key, len(rp)
        return best_key

    def resolve_route(self, path: str):
        """route_for_prefix + a forced refresh on miss -> (key, entry
        dict) or (None, None). The shared routing lookup for BOTH
        ingress proxies (HTTP and gRPC)."""
        key = self.route_for_prefix(path)
        if key is None:
            self._refresh(force=True)
            key = self.route_for_prefix(path)
        if key is None:
            return None, None
        with self._lock:
            return key, dict(self._table.get(key) or {})

    def _replica_handle(self, name: str):
        h = self._handles.get(name)
        if h is None:
            h = ray_tpu.get_actor(name)
            self._handles[name] = h
        return h

    def pick(self, deployment_key: str):
        """Pow-2 choice among breaker-available replicas ->
        (replica_name, actor_handle). Replicas with an OPEN breaker are
        shed; if every replica is open, fall back to the full set (total
        outage is worse than probing a suspect)."""
        self._refresh()
        entry = self._table.get(deployment_key)
        if not entry or not entry["replica_names"]:
            self._refresh(force=True)
            entry = self._table.get(deployment_key)
            if not entry or not entry["replica_names"]:
                raise RuntimeError(
                    f"no replicas for deployment {deployment_key}")
        names = entry["replica_names"]
        healthy = [n for n in names if self._breaker.available(n)]
        if len(healthy) < len(names):
            telemetry.inc("ray_tpu_serve_replica_sheds_total",
                          len(names) - len(healthy),
                          {"deployment": deployment_key})
            from ray_tpu.util import flight_recorder

            flight_recorder.record(
                "serve", "replica_shed", severity="warn",
                deployment=deployment_key,
                shed=len(names) - len(healthy), total=len(names))
        candidates = healthy or names
        if len(candidates) == 1:
            name = candidates[0]
        else:
            a, b = random.sample(candidates, 2)
            name = a if self._qlen.get(a, 0) <= self._qlen.get(b, 0) else b
        return name, self._replica_handle(name)

    def assign(self, deployment_key: str, method_name: str, args, kwargs,
               trace_carrier=None, stream: bool = False):
        """Route one request. ``trace_carrier`` parents the router span
        when the caller's span lives on another thread/process (the
        proxy's event loop, a composing replica) — thread-local context
        does not survive the executor hop, so the carrier rides
        explicitly and continues into the replica via a hidden kwarg.

        ``stream=True`` routes to the replica's streaming lane instead:
        the return value is an ObjectRefGenerator of chunk refs, with
        the deployment's ``max_queued_stream_chunks`` applied as the
        replica-side backpressure window."""
        if trace_carrier is None and tracing.is_enabled():
            trace_carrier = tracing.inject_context()
        with contextlib.ExitStack() as stack:
            # ExitStack so a raising assignment closes the span with
            # the real exception info (error status on otel spans).
            if tracing.is_enabled():
                stack.enter_context(
                    tracing.span(f"router {deployment_key}",
                                 trace_carrier))
                child = tracing.inject_context()
                if child:
                    kwargs = dict(kwargs)
                    kwargs["__serve_trace_ctx"] = child
            t0 = time.time()
            try:
                return self._assign_policy.execute_sync(
                    lambda: self._assign_once(deployment_key, method_name,
                                              args, kwargs, t0, stream),
                    label=f"serve assign {deployment_key}")
            except Exception as e:
                raise RuntimeError(f"could not assign request: {e}")

    def _assign_once(self, deployment_key: str, method_name: str,
                     args, kwargs, t0=None, stream: bool = False):
        try:
            name, handle = self.pick(deployment_key)
        except RuntimeError:
            # pick() force-refreshed before raising: a key absent from a
            # FRESH table is a deleted deployment — fail fast instead of
            # burning the scale-from-zero wait on a route that will
            # never come back under this key.
            with self._lock:
                known = deployment_key in self._table
            if not known:
                raise RuntimeError(
                    f"deployment {deployment_key} is not deployed")
            # No replicas: report the queued request (scale-from-zero
            # signal) and wait for the autoscaler to bring one up.
            ray_tpu.get(self._controller.report_pending_request.remote(
                deployment_key), timeout=self._control_timeout)
            deadline = time.time() + self._scale_wait_timeout
            name = None
            while time.time() < deadline:
                time.sleep(0.25)
                try:
                    name, handle = self.pick(deployment_key)
                    break
                except RuntimeError:
                    continue
            if name is None:
                raise RuntimeError(
                    f"no replicas for {deployment_key} after "
                    f"{self._scale_wait_timeout:.0f}s scale-from-zero "
                    f"wait")
        with self._lock:
            self._qlen[name] = self._qlen.get(name, 0) + 1
        self._report_queue_depth(deployment_key)
        try:
            if stream:
                window = int((self._table.get(deployment_key) or {}).get(
                    "max_queued_stream_chunks", 16))
                gen = handle.handle_request_streaming.options(
                    num_returns="streaming",
                    max_queued_stream_chunks=window,
                ).remote(method_name, args, kwargs)
            else:
                ref = handle.handle_request.remote(method_name, args,
                                                   kwargs)
        except Exception:
            # Replica died between table refreshes; trip its breaker,
            # drop it and let the policy retry against the rest.
            with self._lock:
                self._qlen[name] = max(0, self._qlen.get(name, 1) - 1)
                self._handles.pop(name, None)
            self._breaker.record_failure(name)
            self._refresh(force=True)
            raise
        self._breaker.record_success(name)
        if stream:
            self._attach_stream_completion(name, gen, deployment_key, t0)
            return gen
        self._attach_completion(name, ref, deployment_key, t0)
        return ref

    def _report_queue_depth(self, deployment_key: str) -> None:
        """Current (not peak) ongoing-request depth for one deployment,
        reported on BOTH send and completion."""
        with self._lock:
            entry = self._table.get(deployment_key) or {}
            depth = sum(self._qlen.get(n, 0)
                        for n in entry.get("replica_names", ()))
        telemetry.set_gauge("ray_tpu_serve_router_queue_depth", depth,
                            {"deployment": deployment_key,
                             "proc": telemetry.proc_tag()})

    def _attach_stream_completion(self, name: str, gen, deployment_key,
                                  t0):
        """Stream-lifecycle accounting: TTFT on the first chunk, queue
        depth + chunk/abort counters + breaker verdict at terminal.
        Callbacks fire from the owner loop (producer finish) or the
        consumer thread (release) — everything here is lock-safe."""
        from ray_tpu.util import flight_recorder

        flight_recorder.record("serve", "stream_started",
                               deployment=deployment_key, replica=name)

        def first_chunk():
            if t0 is not None:
                ttft = max(0.0, time.time() - t0)
                telemetry.observe("ray_tpu_serve_stream_ttft_seconds",
                                  ttft, {"deployment": deployment_key})
                # Feed the autoscaler: batched to the controller with
                # the next routing refresh.
                self._note_ttft(deployment_key, ttft)

        # NB: `done` receives the generator as an argument instead of
        # closing over `gen` — a gen-capturing closure stored in
        # gen._done_cbs would be a reference cycle, and abandoned
        # streams must die by refcount (that drop IS the cancel signal).
        def done(tag, g):
            with self._lock:
                self._qlen[name] = max(0, self._qlen.get(name, 1) - 1)
            self._report_queue_depth(deployment_key)
            telemetry.inc("ray_tpu_serve_stream_chunks_total",
                          g.items_produced(),
                          {"deployment": deployment_key})
            if t0 is not None:
                telemetry.observe("ray_tpu_serve_request_latency_seconds",
                                  max(0.0, time.time() - t0),
                                  {"deployment": deployment_key})
            if tag == "ok":
                self._breaker.record_success(name)
                return
            reason = self._stream_abort_reason(g, tag)
            telemetry.inc("ray_tpu_serve_stream_aborts_total", 1,
                          {"deployment": deployment_key,
                           "reason": reason})
            flight_recorder.record(
                "serve", "stream_aborted", severity="warn",
                deployment=deployment_key, replica=name, reason=reason,
                chunks=g.items_produced())
            if reason == "replica_death":
                # Mid-stream deaths count toward the per-replica
                # breaker exactly like failed sends.
                self._breaker.record_failure(name)

        gen.add_first_item_callback(first_chunk)
        gen.add_done_callback(done)

    @staticmethod
    def _stream_abort_reason(gen, tag: str) -> str:
        if tag == "released":
            # The consumer walked away; whoever released may have
            # annotated why (the proxy tags chunk-deadline releases).
            return getattr(gen, "_release_reason", "client_disconnect")
        err = gen.error()
        if isinstance(err, exc.ACTOR_SYSTEM_FAILURES):
            return "replica_death"
        if isinstance(err, exc.GetTimeoutError):
            return "deadline"
        return "app_error"

    def _attach_completion(self, name: str, ref, deployment_key=None,
                           t0=None):
        def done(_):
            with self._lock:
                self._qlen[name] = max(0, self._qlen.get(name, 1) - 1)
            if deployment_key is not None:
                self._report_queue_depth(deployment_key)
            if t0 is not None:
                telemetry.observe("ray_tpu_serve_request_latency_seconds",
                                  max(0.0, time.time() - t0),
                                  {"deployment": deployment_key})

        ref.future().add_done_callback(done)
