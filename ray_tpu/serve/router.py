"""Router: replica choice for a deployment.

Reference: python/ray/serve/_private/router.py:287 +
replica_scheduler/pow_2_scheduler.py:51 — pick two random replicas,
route to the one with fewer ongoing requests. Queue lengths are tracked
router-locally (incremented on send, decremented on completion), the
same local-information design as the reference; the routing table is
refreshed from the controller when its version moves.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class Router:
    def __init__(self, controller_handle, refresh_period_s: float = 1.0):
        self._controller = controller_handle
        self._refresh_period = refresh_period_s
        self._lock = threading.Lock()
        self._version = -1
        self._last_refresh = 0.0
        # deployment key -> list of replica actor names
        self._table: Dict[str, dict] = {}
        self._handles: Dict[str, Any] = {}  # replica name -> actor handle
        self._qlen: Dict[str, int] = {}

    def _refresh(self, force: bool = False):
        now = time.time()
        if not force and now - self._last_refresh < self._refresh_period:
            return
        snap = ray_tpu.get(
            self._controller.get_routing_snapshot.remote(), timeout=30)
        with self._lock:
            self._last_refresh = now
            if snap["version"] != self._version:
                self._version = snap["version"]
                self._table = snap["table"]
                live = {n for e in self._table.values()
                        for n in e["replica_names"]}
                self._handles = {n: h for n, h in self._handles.items()
                                 if n in live}
                self._qlen = {n: q for n, q in self._qlen.items()
                              if n in live}

    def route_for_prefix(self, path: str) -> Optional[str]:
        """Longest-prefix route match (proxy use)."""
        self._refresh()
        best_key, best_len = None, -1
        for key, entry in self._table.items():
            rp = entry.get("route_prefix")
            if rp is None:
                continue
            if (path == rp or path.startswith(rp.rstrip("/") + "/")
                    or rp == "/"):
                if len(rp) > best_len:
                    best_key, best_len = key, len(rp)
        return best_key

    def _replica_handle(self, name: str):
        h = self._handles.get(name)
        if h is None:
            h = ray_tpu.get_actor(name)
            self._handles[name] = h
        return h

    def pick(self, deployment_key: str):
        """Pow-2 choice -> (replica_name, actor_handle)."""
        self._refresh()
        entry = self._table.get(deployment_key)
        if not entry or not entry["replica_names"]:
            self._refresh(force=True)
            entry = self._table.get(deployment_key)
            if not entry or not entry["replica_names"]:
                raise RuntimeError(
                    f"no replicas for deployment {deployment_key}")
        names = entry["replica_names"]
        if len(names) == 1:
            name = names[0]
        else:
            a, b = random.sample(names, 2)
            name = a if self._qlen.get(a, 0) <= self._qlen.get(b, 0) else b
        return name, self._replica_handle(name)

    def assign(self, deployment_key: str, method_name: str, args, kwargs):
        last_err = None
        for attempt in range(3):
            try:
                name, handle = self.pick(deployment_key)
            except RuntimeError as e:
                # No replicas: report the queued request (scale-from-zero
                # signal) and wait for the autoscaler to bring one up.
                last_err = e
                ray_tpu.get(self._controller.report_pending_request.remote(
                    deployment_key), timeout=30)
                deadline = time.time() + 30
                name = None
                while time.time() < deadline:
                    time.sleep(0.25)
                    try:
                        name, handle = self.pick(deployment_key)
                        break
                    except RuntimeError:
                        continue
                if name is None:
                    continue
            with self._lock:
                self._qlen[name] = self._qlen.get(name, 0) + 1
            try:
                ref = handle.handle_request.remote(method_name, args, kwargs)
            except Exception as e:
                # Replica died between table refreshes; drop and retry.
                last_err = e
                with self._lock:
                    self._qlen[name] = max(0, self._qlen.get(name, 1) - 1)
                    self._handles.pop(name, None)
                self._refresh(force=True)
                continue
            self._attach_completion(name, ref)
            return ref
        raise RuntimeError(f"could not assign request: {last_err}")

    def _attach_completion(self, name: str, ref):
        def done(_):
            with self._lock:
                self._qlen[name] = max(0, self._qlen.get(name, 1) - 1)

        ref.future().add_done_callback(done)
