"""DeploymentHandle: Python-level calls into a deployment.

Reference: python/ray/serve/handle.py:830 — handles are the composition
primitive: deployments receive handles to other deployments as bound
arguments and fan out calls. ``handle.remote()`` returns a
DeploymentResponse (future-like); responses can be passed directly as
arguments to downstream handle calls, which forwards the underlying
ObjectRef so the value never round-trips the caller.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME
from ray_tpu.serve.router import Router

_router_lock = threading.Lock()
_router: Optional[Router] = None
# Handle calls issued from inside an event loop (async replicas doing
# composition) offload the router's blocking control calls here; blocking
# the loop would deadlock the replica's own RPC processing.
_offload = concurrent.futures.ThreadPoolExecutor(
    max_workers=8, thread_name_prefix="serve-handle")


def _get_router() -> Router:
    global _router
    with _router_lock:
        if _router is None:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            _router = Router(controller)
        return _router


def _reset_router():
    global _router
    with _router_lock:
        _router = None


class DeploymentResponse:
    """Future-like result of a handle call (reference: handle.py
    DeploymentResponse). The default resolve/result timeout comes from
    ``serve_handle_resolve_timeout_s`` in core/config.py
    (RAY_TPU_SERVE_HANDLE_RESOLVE_TIMEOUT_S)."""

    _UNSET = object()

    def __init__(self, ref=None, ref_future=None):
        self._ref = ref
        self._ref_future = ref_future

    def _resolve_ref(self, timeout=_UNSET):
        if timeout is DeploymentResponse._UNSET:
            from ray_tpu.core.config import get_config

            timeout = get_config().serve_handle_resolve_timeout_s
        if self._ref is None:
            self._ref = self._ref_future.result(timeout)
        return self._ref

    def result(self, timeout=_UNSET) -> Any:
        if timeout is DeploymentResponse._UNSET:
            from ray_tpu.core.config import get_config

            timeout = get_config().serve_handle_resolve_timeout_s
        return ray_tpu.get(self._resolve_ref(timeout), timeout=timeout)

    def _to_object_ref(self):
        return self._resolve_ref()

    async def _await_impl(self):
        if self._ref is None:
            self._ref = await asyncio.wrap_future(self._ref_future)
        return await self._ref

    def __await__(self):
        return self._await_impl().__await__()


class DeploymentResponseGenerator:
    """Streaming result of ``handle.options(stream=True).remote()``
    (reference: handle.py DeploymentResponseGenerator). Iterable both
    ways — ``for chunk in gen`` from sync code, ``async for chunk in
    gen`` from a replica/event loop — yielding the chunk VALUES in
    order. Dropping or ``cancel()``ing it propagates cancellation to
    the replica so the generator body actually stops."""

    _UNSET = object()

    def __init__(self, gen=None, gen_future=None):
        self._gen = gen
        self._gen_future = gen_future
        self._cancelled = False

    def _resolve(self, timeout=_UNSET):
        if self._gen is None:
            if timeout is DeploymentResponseGenerator._UNSET:
                from ray_tpu.core.config import get_config

                timeout = get_config().serve_handle_resolve_timeout_s
            self._gen = self._gen_future.result(timeout)
            if self._cancelled:
                self._gen.close()
        return self._gen

    # -- sync iteration -------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu

        ref = self._resolve().__next__()
        return ray_tpu.get(ref)

    def next_ready(self, timeout: Optional[float] = None):
        """Next chunk, raising GetTimeoutError if none lands in time.
        ``timeout`` is one overall deadline — the assignment wait, the
        chunk wait, and the value fetch share it."""
        import time as _time

        import ray_tpu

        deadline = (_time.monotonic() + timeout
                    if timeout is not None else None)

        def remaining():
            if deadline is None:
                return None
            return max(0.0, deadline - _time.monotonic())

        from ray_tpu import exceptions as exc

        try:
            gen = self._resolve(remaining() if timeout is not None
                                else DeploymentResponseGenerator._UNSET)
        except concurrent.futures.TimeoutError:
            raise exc.GetTimeoutError(
                "stream assignment not ready in time")
        ref = gen.next_ready(timeout=remaining())
        return ray_tpu.get(ref, timeout=remaining())

    # -- async iteration ------------------------------------------------
    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._gen is None:
            self._gen = await asyncio.wrap_future(self._gen_future)
            if self._cancelled:
                self._gen.close()
        ref = await self._gen.__anext__()
        return await ref

    # -- lifecycle ------------------------------------------------------
    def cancel(self):
        """Stop consuming AND stop the replica-side generator. Safe
        while the assignment is still in flight: the stream is closed
        the moment it resolves."""
        self._cancelled = True
        if self._gen is not None:
            self._gen.close()
            return
        if self._gen_future is not None:
            def _close_when_ready(fut):
                if fut.cancelled() or fut.exception() is not None:
                    return
                try:
                    fut.result().close()
                except Exception:
                    pass

            self._gen_future.add_done_callback(_close_when_ready)

    close = cancel

    def completed(self) -> bool:
        return self._gen is not None and self._gen.completed()


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str,
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "",
                 stream: bool = False):
        self._app = app_name
        self._deployment = deployment_name
        self._method = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream

    @property
    def deployment_key(self) -> str:
        return f"{self._app}#{self._deployment}"

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._app, self._deployment,
            method_name or self._method,
            (multiplexed_model_id if multiplexed_model_id is not None
             else self._multiplexed_model_id),
            self._stream if stream is None else bool(stream))

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._app, self._deployment, name,
                                self._multiplexed_model_id, self._stream)

    def remote(self, *args, **kwargs):
        """Route one call. Returns a DeploymentResponse, or a
        DeploymentResponseGenerator when the handle was configured with
        ``options(stream=True)`` (the deployment method must then be a
        generator / async generator)."""
        args = tuple(
            a._to_object_ref() if isinstance(a, DeploymentResponse) else a
            for a in args)
        kwargs = {
            k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                else v)
            for k, v in kwargs.items()}
        if self._multiplexed_model_id:
            kwargs["__serve_multiplexed_model_id"] = \
                self._multiplexed_model_id
        # Capture the caller's trace context on THIS thread: composition
        # calls offload to the handle executor, where thread-local span
        # state is gone.
        from ray_tpu.util import tracing

        carrier = tracing.inject_context() if tracing.is_enabled() else None
        stream = self._stream
        try:
            asyncio.get_running_loop()
            on_loop = True
        except RuntimeError:
            on_loop = False
        if on_loop:
            fut = _offload.submit(
                lambda: _get_router().assign(
                    self.deployment_key, self._method, args, kwargs,
                    trace_carrier=carrier, stream=stream))
            if stream:
                return DeploymentResponseGenerator(gen_future=fut)
            return DeploymentResponse(ref_future=fut)
        out = _get_router().assign(self.deployment_key, self._method,
                                   args, kwargs, trace_carrier=carrier,
                                   stream=stream)
        if stream:
            return DeploymentResponseGenerator(out)
        return DeploymentResponse(out)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._app, self._deployment, self._method,
                 self._multiplexed_model_id, self._stream))

    def __repr__(self):
        return (f"DeploymentHandle({self._app}#{self._deployment}"
                f".{self._method})")
