"""Zero-copy weight delivery: ``train → serve`` handoff at serve scale.

The dominant cost of scaling out an LLM deployment is getting the
weights onto the new replica (TPU serving studies measure cold-start /
weight-delivery time as a first-order serving cost — arxiv 2605.25645).
This module turns weight handoff into a device-plane publish:

- ``publish_weights(name, pytree)`` — a trainer (a gang worker between
  steps, or the driver after ``fit()``) puts the sharded pytree through
  the device-native object plane (``core/device_objects.py``): weights
  stay as per-shard device buffers; only a descriptor envelope is
  serialized. The ref is recorded under ``name`` in the head KV with a
  monotonically increasing version.
- ``fetch_weights(name)`` — a Serve replica resolves the latest ref in
  its ``__init__``: same-process hits are returned by reference,
  remote hits are per-shard pulls from the NEAREST holder — and since
  every consumer registers as a holder, the second replica of a
  deployment cold-starts from the first replica (or any trainer) rather
  than re-reading a checkpoint or hammering the original producer.

The driver keeps the published ref alive in the KV entry itself: the
pickled ref carries a borrow on the owner, so publish-then-exit-scope
does not free the weights under the replicas.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional, Tuple

_KV_NS = "serve_weights"

# name -> (ref, version) of the newest fetch in THIS process. Holding
# the ref keeps the process's device-plane registry copy alive (a
# borrower stops serving shards when its last ref dies), so a replica
# that fetched weights keeps serving peers for as long as it serves the
# model — exactly the cold-start-from-peer window. Replaced (and the old
# version's borrow released) when a newer version is fetched.
_held: dict = {}


def _worker():
    from ray_tpu.api import _require_worker

    return _require_worker()


def publish_weights(name: str, pytree: Any) -> Tuple[Any, int]:
    """Publish a (sharded) weight pytree under ``name``.

    Returns (ObjectRef, version). Re-publishing the same name bumps the
    version; fetchers always resolve the newest. The previous version's
    ref is dropped from the KV, so its device buffers are reclaimed once
    the last replica still holding it releases its borrow.

    Publishes of one ``name`` must come from a single process at a time
    (the normal topology: rank 0 of the gang, or the driver) — the
    version bump and the superseded version's pin release are a
    read-modify-write on the KV entry, not an atomic swap, so
    concurrent republishers can double-release the old pin and lose a
    version."""
    import ray_tpu

    cw = _worker()
    ref = ray_tpu.put(pytree)
    key = f"weights:{name}".encode()
    reply = cw.loop_thread.run(cw.head.call("kv_get",
                                            {"ns": _KV_NS, "key": key}))
    version = 0
    blob = reply.get("value")
    if blob:
        try:
            old = pickle.loads(bytes(blob))
            version = old["version"]
        except Exception:
            old = None
        if old is not None:
            # Release the superseded version's borrow pin — otherwise
            # every re-publish would leak the previous weights for the
            # owner's lifetime (see unpublish for the accounting).
            prev = old["ref"]
            owner = prev.owner_address
            if owner is None or owner.key() == cw.address.key():
                cw.reference_counter.on_borrow_removed(prev.id)
    version += 1
    cw.loop_thread.run(cw.head.call("kv_put", {
        "ns": _KV_NS, "key": key,
        "value": pickle.dumps({"version": version, "ref": ref},
                              protocol=5),
        "overwrite": True,
    }))
    # Version mirrored under its own key so weights_version() polls are
    # one tiny kv_get — no ref deserialization, no borrow churn on the
    # owner.
    cw.loop_thread.run(cw.head.call("kv_put", {
        "ns": _KV_NS, "key": f"weights_ver:{name}".encode(),
        "value": str(version).encode(), "overwrite": True,
    }))
    return ref, version


def fetch_weights(name: str, timeout: Optional[float] = 120.0,
                  donate: bool = False) -> Any:
    """Resolve the latest published weights for ``name``.

    Device-plane semantics apply: the producing process gets its own
    arrays back by reference; other processes pull per-shard from the
    nearest registered holder and become holders themselves (so later
    replicas pull from peers). ``donate=True`` releases the serving
    holder's buffers after the transfer."""
    entry = published_ref(name)
    if entry is None:
        raise KeyError(f"no published weights under {name!r}")
    ref, version = entry
    import ray_tpu

    value = ray_tpu.get(ref, timeout=timeout, donate=donate)
    _held[name] = (ref, version)
    return value


def published_ref(name: str) -> Optional[Tuple[Any, int]]:
    """(ref, version) of the latest publish, or None.

    Borrow accounting: the publish-time pickle counted ONE borrow on the
    owner, but the KV blob is deserialized once per fetcher — each of
    which will send a matching remove_ref when its ref dies. Every load
    beyond the one that unpublish() consumes must therefore add its own
    borrow, or the N-th fetch would drive the owner's count negative and
    free the weights under live replicas."""
    cw = _worker()
    key = f"weights:{name}".encode()
    reply = cw.loop_thread.run(cw.head.call("kv_get",
                                            {"ns": _KV_NS, "key": key}))
    blob = reply.get("value")
    if not blob:
        return None
    entry = pickle.loads(bytes(blob))
    ref = entry["ref"]
    owner = ref.owner_address
    if owner is not None and owner.key() != cw.address.key():
        cw.reference_counter.on_ref_serialized(ref)
    return ref, entry["version"]


def weights_version(name: str) -> int:
    """Latest published version (0 = never published). One small
    kv_get — no ref materialization or refcount traffic — so a replica
    health loop can poll it to decide when to re-fetch."""
    cw = _worker()
    reply = cw.loop_thread.run(cw.head.call("kv_get", {
        "ns": _KV_NS, "key": f"weights_ver:{name}".encode()}))
    blob = reply.get("value")
    if not blob:
        return 0
    try:
        return int(bytes(blob).decode())
    except ValueError:
        return 0


def unpublish(name: str) -> None:
    """Drop the KV entry and release the publish-time borrow pin (the
    registry copies held by replicas drain via their own refcounts)."""
    cw = _worker()
    key = f"weights:{name}".encode()
    reply = cw.loop_thread.run(cw.head.call("kv_get",
                                            {"ns": _KV_NS, "key": key}))
    cw.loop_thread.run(cw.head.call("kv_del",
                                    {"ns": _KV_NS, "key": key}))
    cw.loop_thread.run(cw.head.call("kv_del", {
        "ns": _KV_NS, "key": f"weights_ver:{name}".encode()}))
    blob = reply.get("value")
    if not blob:
        return
    try:
        entry = pickle.loads(bytes(blob))
    except Exception:
        return
    ref = entry["ref"]
    owner = ref.owner_address
    if owner is None or owner.key() == cw.address.key():
        # Owner-side unpublish: this load only touched the local count;
        # cancel the publish-time borrow explicitly.
        cw.reference_counter.on_borrow_removed(ref.id)
    # Remote unpublish: this loaded ref's destruction sends the
    # remove_ref that cancels the publish-time borrow.
