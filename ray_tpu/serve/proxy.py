"""HTTP proxy: the data-plane ingress.

Reference: python/ray/serve/_private/proxy.py:1115 (ProxyActor hosting
an HTTP server that routes by prefix and forwards to replicas via the
router). aiohttp replaces uvicorn/starlette; the user callable receives
a ``Request`` with method/path/query/body helpers, and return values
map to JSON (dict/list), text (str), or raw bytes responses.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)

PROXY_NAME = "SERVE_PROXY"

# Sentinel: the stream produced no chunks (StopAsyncIteration before
# the first item).
_STREAM_END = object()


class Request:
    """Minimal request container handed to ingress callables (reference
    passes a starlette Request; the shape here is the commonly used
    subset)."""

    def __init__(self, method: str, path: str, query: dict, headers: dict,
                 body: bytes):
        self.method = method
        self.path = path
        self.query_params = query
        self.headers = headers
        self._body = body

    def body(self) -> bytes:
        return self._body

    def json(self) -> Any:
        return json.loads(self._body) if self._body else None

    def text(self) -> str:
        return self._body.decode()

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query_params,
                          self.headers, self._body))


class ProxyActor:
    """Async actor running an aiohttp server; one per node in the
    reference — one per cluster here (single-host head runtime)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from ray_tpu.core.config import get_config

        self.host = host
        self.port = port
        self._runner = None
        self._router = None
        self._max_body = get_config().serve_max_request_body_bytes
        self._started = asyncio.get_event_loop().create_task(self._start())
        # gRPC ingress next to HTTP (reference: proxy.py:542 gRPCProxy);
        # it runs its own thread pool, so the actor's event loop never
        # blocks on it.
        from ray_tpu.serve.grpc_proxy import GrpcProxy

        try:
            # Loopback unless explicitly opened: the gRPC ingress
            # unpickles request payloads (trusted-client protocol), so
            # it must not silently ride the HTTP host onto 0.0.0.0.
            import os as _os

            grpc_host = _os.environ.get("RAY_TPU_SERVE_GRPC_HOST",
                                        "127.0.0.1")
            self._grpc = GrpcProxy(self._get_router, host=grpc_host,
                                   port=0)
            self.grpc_port = self._grpc.port
        except Exception:
            logger.exception("gRPC ingress unavailable")
            self._grpc = None
            self.grpc_port = None

    async def get_grpc_port(self):
        return self.grpc_port

    def _get_router(self):
        if self._router is None:
            import ray_tpu
            from ray_tpu.serve.controller import CONTROLLER_NAME
            from ray_tpu.serve.router import Router

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            self._router = Router(controller)
        return self._router

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        logger.info("serve proxy listening on %s:%d", self.host, self.port)

    async def ready(self) -> int:
        await self._started
        return self.port

    async def _handle(self, request):
        from aiohttp import web

        # The router's control calls (get_actor, routing-table fetch) are
        # blocking; everything router-touching runs off-loop — blocking
        # this actor's event loop would stall its own RPC processing.
        loop = asyncio.get_event_loop()
        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            def routes_sync():
                router = self._get_router()
                router._refresh(force=True)
                return {e["route_prefix"]: key
                        for key, e in router._table.items()
                        if e.get("route_prefix")}

            return web.json_response(
                await loop.run_in_executor(None, routes_sync))
        # Stream the request body in (long prompts arrive as chunked
        # uploads): accumulate bounded by serve_max_request_body_bytes
        # and reject with an honest 413 the moment the bound is crossed
        # — request.read() would buffer the whole body first and only
        # then let us look at its size.
        body = await self._read_body_bounded(request)
        if body is None:
            from ray_tpu.util import telemetry

            telemetry.inc("ray_tpu_serve_http_requests_total", 1,
                          {"route": "body_limit", "code": "413"})
            return web.Response(
                status=413,
                text=f"request body exceeds "
                     f"serve_max_request_body_bytes={self._max_body}")
        req = Request(request.method, path, dict(request.query),
                      dict(request.headers), body)

        model_id = request.headers.get("serve_multiplexed_model_id", "")

        from ray_tpu.util import telemetry, tracing

        tracing.maybe_setup_worker_tracing()
        t0 = time.perf_counter()
        if tracing.is_enabled():
            # The proxy span is the trace root of an HTTP request; its
            # carrier hops to the router's executor thread explicitly
            # (thread-local context doesn't survive run_in_executor) and
            # from there into the replica, so one trace id spans
            # proxy -> router -> replica across processes.
            with tracing.span(f"proxy {request.method} {path}"):
                carrier = tracing.inject_context()
                route, resp = await self._dispatch(loop, path, req,
                                                   model_id, carrier,
                                                   request)
        else:
            route, resp = await self._dispatch(loop, path, req,
                                               model_id, None, request)
        telemetry.observe("ray_tpu_serve_http_latency_seconds",
                          time.perf_counter() - t0, {"route": route})
        telemetry.inc("ray_tpu_serve_http_requests_total", 1,
                      {"route": route, "code": str(resp.status)})
        return resp

    async def _read_body_bounded(self, request) -> Optional[bytes]:
        """Incrementally accumulate the request body (fixed-length OR
        chunked transfer), returning None once it exceeds the
        configured cap — the connection stops reading right there
        instead of swallowing the rest of an oversized upload."""
        declared = request.content_length
        if declared is not None and declared > self._max_body:
            return None
        buf = bytearray()
        while True:
            chunk = await request.content.readany()
            if not chunk:
                return bytes(buf)
            buf.extend(chunk)
            if len(buf) > self._max_body:
                return None

    async def _dispatch(self, loop, path, req, model_id, carrier,
                        http_request):
        """Route + await one request; returns (route tag, response).
        Generator deployments (routing-table ``stream`` flag) take the
        streaming path: SSE or chunked transfer, first chunk flushed the
        moment the replica yields it."""
        from aiohttp import web

        kwargs = ({"__serve_multiplexed_model_id": model_id}
                  if model_id else {})
        # One executor hop for the unary hot path: route AND assign in
        # the same blocking call; only streaming routes come back to the
        # loop between the two (the stream needs loop-side framing).
        routed = {}

        def route_and_assign():
            key, entry = self._route_blocking(path)
            if key is None:
                return None
            routed["key"] = key
            routed["entry"] = entry
            if entry.get("stream"):
                return None
            return self._get_router().assign(
                key, "__call__", (req,), kwargs, trace_carrier=carrier)

        try:
            ref = await loop.run_in_executor(None, route_and_assign)
        except Exception as e:
            logger.exception("proxy request failed")
            return routed.get("key", "unmatched"), web.Response(
                status=500, text=str(e))
        key = routed.get("key")
        if key is None:
            return "unmatched", web.Response(
                status=404, text=f"no route for {path}")
        if routed["entry"].get("stream"):
            return await self._dispatch_stream(
                loop, path, key, routed["entry"], req, kwargs, carrier,
                http_request)
        try:
            result = await ref
        except Exception as e:
            logger.exception("proxy request failed")
            return key, web.Response(status=500, text=str(e))
        return key, _to_response(result)

    def _route_blocking(self, path):
        """(executor thread) Longest-prefix route -> (key, entry dict),
        or (None, None) when nothing matches."""
        return self._get_router().resolve_route(path)

    async def _dispatch_stream(self, loop, path, key, entry, req,
                               kwargs, carrier, http_request):
        """Stream a generator deployment's chunks to the HTTP client.

        Framing: SSE (``text/event-stream``) when the deployment pins
        ``stream_format="sse"`` or negotiates it via the Accept header,
        otherwise chunked transfer. Mid-stream replica failure surfaces
        as a terminal error event (SSE ``event: error`` / a
        ``[stream-error]`` trailer chunk) — never a silent hang; client
        disconnect propagates cancellation back to the replica so its
        generator stops."""
        from aiohttp import web

        from ray_tpu.core.config import get_config

        def assign_stream():
            return self._get_router().assign(
                key, "__call__", (req,), kwargs, trace_carrier=carrier,
                stream=True)

        def force_refresh():
            try:
                self._get_router()._refresh(force=True)
            except Exception as e:
                from ray_tpu.util import flight_recorder

                # The retry proceeds against the stale table.
                flight_recorder.swallow("proxy.stream_table_refresh", e)

        chunk_timeout = get_config().serve_stream_chunk_timeout_s
        # Acquire the stream AND its first chunk before committing HTTP
        # headers: a failure this early (stale routing table pointing at
        # a dead replica) is retried against a refreshed table — safe
        # because nothing was delivered yet — and a terminal failure
        # becomes an honest 500/504 instead of a 200 with an error
        # trailer.
        gen = None
        first = _STREAM_END
        last_err: Optional[Exception] = None
        for attempt in range(3):
            try:
                gen = await loop.run_in_executor(None, assign_stream)
            except Exception as e:
                logger.exception("proxy stream assignment failed")
                return key, web.Response(status=500, text=str(e))
            try:
                ref = await asyncio.wait_for(gen.__anext__(),
                                             timeout=chunk_timeout)
                first = await ref
                break
            except asyncio.CancelledError:
                # Client disconnected while we waited for the first
                # chunk (pre-headers): the replica-side generator must
                # not keep producing.
                gen.close()
                raise
            except StopAsyncIteration:
                first = _STREAM_END
                break
            except asyncio.TimeoutError:
                gen._release_reason = "deadline"
                gen.close()
                return key, web.Response(
                    status=504,
                    text=f"no first chunk within {chunk_timeout:.0f}s "
                         "(stream deadline)")
            except Exception as e:
                last_err = e
                gen.close()
                gen = None
                if not _is_replica_system_error(e):
                    # Application error before the first chunk: the
                    # user generator ran (and may have side-effected) —
                    # re-executing it on another replica would duplicate
                    # that work. Fail once, like the unary path.
                    break
                # The failure may mean the route itself moved (redeploy
                # at this prefix): refresh and re-resolve before the
                # next attempt.
                await loop.run_in_executor(None, force_refresh)
                new_key, new_entry = await loop.run_in_executor(
                    None, self._route_blocking, path)
                if new_key is None:
                    return key, web.Response(
                        status=404, text=f"no route for {path}")
                key, entry = new_key, new_entry
                if not entry.get("stream"):
                    # Replaced by a non-generator deployment mid-retry.
                    return await self._dispatch(
                        loop, path, req,
                        kwargs.get("__serve_multiplexed_model_id", ""),
                        carrier, http_request)
        if gen is None:
            logger.warning("proxy stream failed before first chunk: %s",
                           last_err)
            return key, web.Response(status=500, text=str(last_err))

        accept = http_request.headers.get("Accept", "")
        fmt = entry.get("stream_format", "auto")
        use_sse = fmt == "sse" or (fmt == "auto"
                                   and "text/event-stream" in accept)
        resp = web.StreamResponse(status=200)
        if use_sse:
            resp.headers["Content-Type"] = "text/event-stream"
            resp.headers["Cache-Control"] = "no-cache"
        else:
            resp.headers["Content-Type"] = "application/octet-stream"
        resp.enable_chunked_encoding()
        try:
            await resp.prepare(http_request)
        except Exception:
            gen.close()
            return key, resp
        try:
            wrote_first = False
            while True:
                try:
                    if not wrote_first:
                        value = first
                        wrote_first = True
                        if value is _STREAM_END:
                            raise StopAsyncIteration
                    else:
                        ref = await asyncio.wait_for(
                            gen.__anext__(), timeout=chunk_timeout)
                        value = await ref
                except StopAsyncIteration:
                    if use_sse:
                        await resp.write(b"event: end\ndata:\n\n")
                    break
                except asyncio.TimeoutError:
                    # Hung replica: conn alive, no chunks. Tag the
                    # release so the router's abort counter says
                    # "deadline", then tell the client.
                    gen._release_reason = "deadline"
                    gen.close()
                    await self._write_stream_error(
                        resp, use_sse,
                        f"no chunk within {chunk_timeout:.0f}s "
                        "(stream deadline)")
                    break
                except Exception as e:
                    # Mid-stream failure (replica death, generator
                    # exception): terminal error chunk, not a hang.
                    gen.close()
                    await self._write_stream_error(resp, use_sse, str(e))
                    break
                try:
                    await resp.write(_encode_chunk(value, use_sse))
                except (ConnectionResetError, ConnectionError, OSError):
                    gen.close()  # client went away -> stop the replica
                    break
        except asyncio.CancelledError:
            # aiohttp cancels the handler on client disconnect; the
            # replica-side generator must not keep producing.
            gen.close()
            raise
        try:
            await resp.write_eof()
        except Exception:  # lint: allow-silent(client already disconnected; stream fully delivered)
            pass
        return key, resp

    @staticmethod
    async def _write_stream_error(resp, use_sse: bool, message: str):
        try:
            if use_sse:
                data = "".join(f"data: {ln}\n"
                               for ln in message.split("\n"))
                await resp.write(
                    b"event: error\n" + data.encode() + b"\n")
            else:
                await resp.write(
                    f"\n[stream-error] {message}\n".encode())
        except Exception:  # lint: allow-silent(client already gone; the router counted the abort)
            pass

    async def shutdown(self):
        if self._grpc is not None:
            self._grpc.stop()
            self._grpc = None
        if self._runner is not None:
            await self._runner.cleanup()


def _is_replica_system_error(e: Exception) -> bool:
    """Did this failure come from the serving system (dead/unreachable
    replica — safe to retry before any chunk was delivered) rather than
    from the user generator's own code (never re-executed)?"""
    from ray_tpu import exceptions as exc

    return isinstance(e, exc.ACTOR_SYSTEM_FAILURES)


def _encode_chunk(value: Any, sse: bool) -> bytes:
    """One stream chunk as wire bytes. Chunked transfer passes bytes
    through raw (str utf-8, dict/list as JSON lines); SSE frames every
    chunk as a ``data:`` event. Text values are framed without a
    bytes round-trip — the token hot path is str/dict chunks."""
    if isinstance(value, bytes):
        if not sse:
            return value
        try:
            text = value.decode()
        except UnicodeDecodeError:
            # SSE is a text protocol; transcoding arbitrary bytes
            # would silently corrupt them. Frame non-UTF-8 chunks
            # honestly as a base64 "binary" event.
            import base64

            return (b"event: binary\ndata: "
                    + base64.b64encode(value) + b"\n\n")
    elif isinstance(value, str):
        text = value
    elif isinstance(value, (dict, list)):
        text = json.dumps(value)
        if not sse:
            return (text + "\n").encode()  # JSONL for chunked readers
    else:
        text = str(value)
    if not sse:
        return text.encode()
    return ("".join(f"data: {ln}\n" for ln in text.split("\n"))
            + "\n").encode()


def _to_response(result: Any):
    from aiohttp import web

    if result is None:
        return web.Response(status=200)
    if isinstance(result, (dict, list)):
        return web.json_response(result)
    if isinstance(result, bytes):
        return web.Response(body=result)
    if isinstance(result, (int, float)):
        return web.Response(text=json.dumps(result),
                            content_type="application/json")
    return web.Response(text=str(result))
