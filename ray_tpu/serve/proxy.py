"""HTTP proxy: the data-plane ingress.

Reference: python/ray/serve/_private/proxy.py:1115 (ProxyActor hosting
an HTTP server that routes by prefix and forwards to replicas via the
router). aiohttp replaces uvicorn/starlette; the user callable receives
a ``Request`` with method/path/query/body helpers, and return values
map to JSON (dict/list), text (str), or raw bytes responses.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)

PROXY_NAME = "SERVE_PROXY"


class Request:
    """Minimal request container handed to ingress callables (reference
    passes a starlette Request; the shape here is the commonly used
    subset)."""

    def __init__(self, method: str, path: str, query: dict, headers: dict,
                 body: bytes):
        self.method = method
        self.path = path
        self.query_params = query
        self.headers = headers
        self._body = body

    def body(self) -> bytes:
        return self._body

    def json(self) -> Any:
        return json.loads(self._body) if self._body else None

    def text(self) -> str:
        return self._body.decode()

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query_params,
                          self.headers, self._body))


class ProxyActor:
    """Async actor running an aiohttp server; one per node in the
    reference — one per cluster here (single-host head runtime)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._runner = None
        self._router = None
        self._started = asyncio.get_event_loop().create_task(self._start())
        # gRPC ingress next to HTTP (reference: proxy.py:542 gRPCProxy);
        # it runs its own thread pool, so the actor's event loop never
        # blocks on it.
        from ray_tpu.serve.grpc_proxy import GrpcProxy

        try:
            # Loopback unless explicitly opened: the gRPC ingress
            # unpickles request payloads (trusted-client protocol), so
            # it must not silently ride the HTTP host onto 0.0.0.0.
            import os as _os

            grpc_host = _os.environ.get("RAY_TPU_SERVE_GRPC_HOST",
                                        "127.0.0.1")
            self._grpc = GrpcProxy(self._get_router, host=grpc_host,
                                   port=0)
            self.grpc_port = self._grpc.port
        except Exception:
            logger.exception("gRPC ingress unavailable")
            self._grpc = None
            self.grpc_port = None

    async def get_grpc_port(self):
        return self.grpc_port

    def _get_router(self):
        if self._router is None:
            import ray_tpu
            from ray_tpu.serve.controller import CONTROLLER_NAME
            from ray_tpu.serve.router import Router

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            self._router = Router(controller)
        return self._router

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        logger.info("serve proxy listening on %s:%d", self.host, self.port)

    async def ready(self) -> int:
        await self._started
        return self.port

    async def _handle(self, request):
        from aiohttp import web

        # The router's control calls (get_actor, routing-table fetch) are
        # blocking; everything router-touching runs off-loop — blocking
        # this actor's event loop would stall its own RPC processing.
        loop = asyncio.get_event_loop()
        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            def routes_sync():
                router = self._get_router()
                router._refresh(force=True)
                return {e["route_prefix"]: key
                        for key, e in router._table.items()
                        if e.get("route_prefix")}

            return web.json_response(
                await loop.run_in_executor(None, routes_sync))
        body = await request.read()
        req = Request(request.method, path, dict(request.query),
                      dict(request.headers), body)

        model_id = request.headers.get("serve_multiplexed_model_id", "")

        from ray_tpu.util import telemetry, tracing

        tracing.maybe_setup_worker_tracing()
        t0 = time.perf_counter()
        if tracing.is_enabled():
            # The proxy span is the trace root of an HTTP request; its
            # carrier hops to the router's executor thread explicitly
            # (thread-local context doesn't survive run_in_executor) and
            # from there into the replica, so one trace id spans
            # proxy -> router -> replica across processes.
            with tracing.span(f"proxy {request.method} {path}"):
                carrier = tracing.inject_context()
                route, resp = await self._dispatch(loop, path, req,
                                                   model_id, carrier)
        else:
            route, resp = await self._dispatch(loop, path, req,
                                               model_id, None)
        telemetry.observe("ray_tpu_serve_http_latency_seconds",
                          time.perf_counter() - t0, {"route": route})
        telemetry.inc("ray_tpu_serve_http_requests_total", 1,
                      {"route": route, "code": str(resp.status)})
        return resp

    async def _dispatch(self, loop, path, req, model_id, carrier):
        """Route + await one request; returns (route tag, response)."""
        from aiohttp import web

        def assign_sync():
            router = self._get_router()
            key = router.route_for_prefix(path)
            if key is None:
                router._refresh(force=True)
                key = router.route_for_prefix(path)
            if key is None:
                return None, None
            kwargs = ({"__serve_multiplexed_model_id": model_id}
                      if model_id else {})
            return key, router.assign(key, "__call__", (req,), kwargs,
                                      trace_carrier=carrier)

        key = None
        try:
            key, ref = await loop.run_in_executor(None, assign_sync)
            if key is None:
                return "unmatched", web.Response(
                    status=404, text=f"no route for {path}")
            result = await ref
        except Exception as e:
            logger.exception("proxy request failed")
            return key or "unmatched", web.Response(status=500,
                                                    text=str(e))
        return key, _to_response(result)

    async def shutdown(self):
        if self._grpc is not None:
            self._grpc.stop()
            self._grpc = None
        if self._runner is not None:
            await self._runner.cleanup()


def _to_response(result: Any):
    from aiohttp import web

    if result is None:
        return web.Response(status=200)
    if isinstance(result, (dict, list)):
        return web.json_response(result)
    if isinstance(result, bytes):
        return web.Response(body=result)
    if isinstance(result, (int, float)):
        return web.Response(text=json.dumps(result),
                            content_type="application/json")
    return web.Response(text=str(result))
