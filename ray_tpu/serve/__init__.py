"""ray_tpu.serve — scalable model serving.

Reference capability: python/ray/serve (deployments, controller-managed
replicas, HTTP ingress, pow-2 routing, autoscaling, batching,
multiplexing). TPU-first: replicas pin chips and warm up compiled
executables before joining the routing table.
"""

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.engine import EngineConfig, EngineOverloadedError, Finished
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.proxy import Request
from ray_tpu.serve.weights import (
    fetch_weights,
    publish_weights,
    unpublish,
    weights_version,
)

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "EngineConfig",
    "EngineOverloadedError",
    "Finished",
    "Request",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "fetch_weights",
    "get_multiplexed_model_id",
    "multiplexed",
    "publish_weights",
    "run",
    "shutdown",
    "start",
    "status",
    "unpublish",
    "weights_version",
]
