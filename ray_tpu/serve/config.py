"""Serve configuration schemas.

Reference: python/ray/serve/config.py (AutoscalingConfig,
DeploymentConfig) and schema.py. Plain dataclasses with validation —
the pydantic dependency is not required for behavioral parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.serve.engine.config import EngineConfig


@dataclasses.dataclass
class AutoscalingConfig:
    """Reference: serve/config.py:AutoscalingConfig — replica count
    tracks avg ongoing requests per replica around a target. The
    streaming/engine signals close the loop for LLM serving: routers
    report observed TTFT with their routing-table refresh, replicas
    report engine batch occupancy and admission queue depth, and the
    controller scales up on a sustained breach of ``target_ttft_s`` /
    ``target_queue_depth`` and down on idle engine occupancy."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0
    look_back_period_s: float = 5.0
    # --- streaming / continuous-batching signals ---
    # Scale up when the look-back-window average TTFT (router-observed
    # serve_stream_ttft_seconds) stays above this for upscale_delay_s.
    target_ttft_s: Optional[float] = None
    # Scale up when the mean engine admission-queue depth per replica
    # stays above this for upscale_delay_s. Engine deployments never
    # upscale on num_ongoing (long-lived streams pin it), so when
    # neither target_ttft_s nor target_queue_depth is set the
    # controller defaults this to 0.0 for them: sustained queueing
    # scales up.
    target_queue_depth: Optional[float] = None
    # Engine deployments scale DOWN (to min_replicas) when batch
    # occupancy / max_batch_size stays at or below this fraction with an
    # empty admission queue for downscale_delay_s.
    downscale_occupancy: float = 0.1

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")
        if self.target_ttft_s is not None and self.target_ttft_s <= 0:
            raise ValueError("target_ttft_s must be > 0")
        if (self.target_queue_depth is not None
                and self.target_queue_depth < 0):
            raise ValueError("target_queue_depth must be >= 0")
        if not 0 <= self.downscale_occupancy < 1:
            raise ValueError("downscale_occupancy must be in [0, 1)")


#: Valid values of ``DeploymentConfig.stream_format``: "auto" negotiates
#: by the request's Accept header (text/event-stream -> SSE, else
#: chunked); "sse"/"chunked" pin the HTTP framing for every client.
STREAM_FORMATS = ("auto", "sse", "chunked")


@dataclasses.dataclass
class DeploymentConfig:
    """Reference: serve/config.py:DeploymentConfig."""

    num_replicas: int = 1
    max_ongoing_requests: int = 100
    user_config: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    graceful_shutdown_timeout_s: float = 10.0
    health_check_period_s: float = 2.0
    # --- streaming (generator deployments) ---
    # Per-stream backpressure: max chunks a replica may have produced
    # but the consumer not yet read before its generator body pauses
    # (credit-based; 0 = unbounded). Bounds replica-side memory when a
    # fast TPU replica feeds a slow client.
    max_queued_stream_chunks: int = 16
    # HTTP framing for streamed responses (see STREAM_FORMATS).
    stream_format: str = "auto"
    # Opt into the iteration-level continuous-batching engine
    # (serve/engine/): requests share a per-replica decode loop that
    # admits new arrivals between iterations instead of per-request
    # generator bodies. None = classic per-request execution.
    engine: Optional[EngineConfig] = None

    def __post_init__(self):
        if self.stream_format not in STREAM_FORMATS:
            raise ValueError(
                f"stream_format must be one of {STREAM_FORMATS}, got "
                f"{self.stream_format!r}")
        if self.max_queued_stream_chunks < 0:
            raise ValueError("max_queued_stream_chunks must be >= 0")

    def initial_replicas(self) -> int:
        if self.autoscaling_config:
            return self.autoscaling_config.min_replicas
        return self.num_replicas


@dataclasses.dataclass
class ReplicaConfig:
    """Actor-level options for replicas; ``num_tpus`` pins the replica to
    a chip — the TPU-first detail: a pinned replica owns its device and
    keeps its compiled executables warm across requests."""

    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Optional[Dict[str, float]] = None
    # Replica placement (reference: deployment_scheduler.py): SPREAD
    # (default — replicas across nodes), PACK (consolidate), DEFAULT
    # (cluster scheduler's choice); cap per node optional.
    placement_strategy: str = "SPREAD"
    max_replicas_per_node: Optional[int] = None

    def __post_init__(self):
        from ray_tpu.serve.scheduler import DeploymentScheduler

        # Invalid policy/cap fails at construction (deploy time), not
        # at reconcile time inside the controller.
        DeploymentScheduler(self.placement_strategy,
                            self.max_replicas_per_node)

    def actor_options(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"num_cpus": self.num_cpus}
        if self.num_tpus:
            out["num_tpus"] = self.num_tpus
        if self.resources:
            out["resources"] = dict(self.resources)
        return out
