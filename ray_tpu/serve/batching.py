"""@serve.batch: dynamic request batching.

Reference: python/ray/serve/batching.py — an async method decorated with
@serve.batch collects concurrent calls into a list; the wrapped function
runs once per batch and its list result is scattered back to callers.
The TPU payoff is direct: batched requests share one XLA executable
launch instead of num_requests launches.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import time
from typing import Any, Callable, List, Optional

from ray_tpu.util import telemetry


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: List[tuple] = []  # (single_arg, future, enqueued_at)
        self._flusher: Optional[asyncio.Task] = None
        self._bg_flushes: set = set()  # keep refs: loop holds tasks weakly

    async def submit(self, instance, arg) -> Any:
        fut = asyncio.get_event_loop().create_future()
        self.queue.append((arg, fut, time.monotonic()))
        if len(self.queue) == self.max_batch_size:
            # Exactly-at-crossing (appends are one at a time, so every
            # crossing hits equality): one flush task per full batch,
            # not one per over-cap submit. Detached, NOT awaited inline
            # on this caller's task: the batch fn serves every parked
            # peer, so one client's cancellation mid-execution must only
            # drop that client's slot — not abort the shared computation
            # for the rest.
            t = asyncio.get_event_loop().create_task(
                self._flush(instance))
            self._bg_flushes.add(t)
            t.add_done_callback(self._bg_flushes.discard)
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_event_loop().create_task(
                self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.timeout)
        await self._flush(instance)

    async def _flush(self, instance):
        # Drain in max_batch_size slices: a same-tick burst can append
        # many entries before this task runs, and the batch fn's
        # contract (XLA executables compiled/padded for <= max) must
        # hold regardless of arrival pattern.
        try:
            while self.queue:
                await self._flush_one(instance)
        except asyncio.CancelledError:
            # Torn down mid-drain (loop shutdown): fail what's still
            # parked — unresolved futures would hang their callers.
            for _, f, _enq in self.queue:
                if not f.done():
                    f.set_exception(
                        RuntimeError("batch flush task cancelled"))
            self.queue = []
            raise

    async def _flush_one(self, instance):
        batch = self.queue[:self.max_batch_size]
        self.queue = self.queue[self.max_batch_size:]
        now = time.monotonic()
        args: List[Any] = []
        futs: List[asyncio.Future] = []
        for a, f, enqueued in batch:
            # A caller cancelled while parked (client disconnected, task
            # torn down) is dropped HERE: executing its slot would spend
            # a batch position computing for a dead client.
            if f.cancelled():
                continue
            telemetry.observe("ray_tpu_serve_batch_queue_wait_seconds",
                              now - enqueued)
            args.append(a)
            futs.append(f)
        if not args:
            return
        try:
            if instance is not None:
                results = self.fn(instance, args)
            else:
                results = self.fn(args)
            if asyncio.iscoroutine(results):
                results = await results
            if inspect.isgenerator(results) or inspect.isasyncgen(
                    results):
                # Scattering a generator like a list would silently
                # hand each caller one exhausted-iterator slice.
                raise TypeError(
                    f"@serve.batch function "
                    f"{getattr(self.fn, '__name__', '?')!r} returned a "
                    "generator; batched streaming is not supported — "
                    "make the deployment itself a generator and call "
                    "it with handle.options(stream=True).remote(...)")
            if len(results) != len(args):
                raise ValueError(
                    f"batch fn returned {len(results)} results for "
                    f"{len(args)} inputs")
            for f, r in zip(futs, results):
                if not f.done():
                    f.set_result(r)
        except BaseException as e:
            # BaseException on purpose: the batch already left
            # self.queue, so ANY abort of this flush task — including a
            # CancelledError raised by the batch fn or loop teardown —
            # must resolve the parked futures or their callers hang
            # forever. (Flushes run on detached/timer tasks, never a
            # caller's task, so caller cancellation cannot land here.)
            for f in futs:
                if not f.done():
                    f.set_exception(
                        RuntimeError("batch flush aborted: "
                                     f"{e!r}")
                        if isinstance(e, asyncio.CancelledError) else e)
            if isinstance(e, asyncio.CancelledError):
                raise


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for async single-request methods; the wrapped fn receives
    a list of requests and returns a list of responses."""

    def wrap(fn):
        if inspect.isgeneratorfunction(fn) or inspect.isasyncgenfunction(fn):
            # Fail at decoration time: a generator body would be
            # scattered like a list result and every caller would get
            # garbage.
            raise TypeError(
                f"@serve.batch cannot wrap generator function "
                f"{getattr(fn, '__name__', '?')!r}; streaming responses "
                "go through generator deployments + "
                "handle.options(stream=True) instead")
        queues = {}  # instance id -> _BatchQueue (methods) / None key (fns)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                instance, arg = args
                key = id(instance)
            elif len(args) == 1:
                instance, arg = None, args[0]
                key = None
            else:
                raise TypeError("@serve.batch methods take one argument")
            q = queues.get(key)
            if q is None:
                q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                queues[key] = q
            return await q.submit(instance, arg)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
