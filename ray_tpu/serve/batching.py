"""@serve.batch: dynamic request batching.

Reference: python/ray/serve/batching.py — an async method decorated with
@serve.batch collects concurrent calls into a list; the wrapped function
runs once per batch and its list result is scattered back to callers.
The TPU payoff is direct: batched requests share one XLA executable
launch instead of num_requests launches.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: List[tuple] = []  # (single_arg, future)
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, instance, arg) -> Any:
        fut = asyncio.get_event_loop().create_future()
        self.queue.append((arg, fut))
        if len(self.queue) >= self.max_batch_size:
            await self._flush(instance)
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_event_loop().create_task(
                self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.timeout)
        await self._flush(instance)

    async def _flush(self, instance):
        if not self.queue:
            return
        batch, self.queue = self.queue, []
        args = [a for a, _ in batch]
        futs = [f for _, f in batch]
        try:
            if instance is not None:
                results = self.fn(instance, args)
            else:
                results = self.fn(args)
            if asyncio.iscoroutine(results):
                results = await results
            if inspect.isgenerator(results) or inspect.isasyncgen(
                    results):
                # Scattering a generator like a list would silently
                # hand each caller one exhausted-iterator slice.
                raise TypeError(
                    f"@serve.batch function "
                    f"{getattr(self.fn, '__name__', '?')!r} returned a "
                    "generator; batched streaming is not supported — "
                    "make the deployment itself a generator and call "
                    "it with handle.options(stream=True).remote(...)")
            if len(results) != len(args):
                raise ValueError(
                    f"batch fn returned {len(results)} results for "
                    f"{len(args)} inputs")
            for f, r in zip(futs, results):
                if not f.done():
                    f.set_result(r)
        except Exception as e:
            for f in futs:
                if not f.done():
                    f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for async single-request methods; the wrapped fn receives
    a list of requests and returns a list of responses."""

    def wrap(fn):
        if inspect.isgeneratorfunction(fn) or inspect.isasyncgenfunction(fn):
            # Fail at decoration time: a generator body would be
            # scattered like a list result and every caller would get
            # garbage.
            raise TypeError(
                f"@serve.batch cannot wrap generator function "
                f"{getattr(fn, '__name__', '?')!r}; streaming responses "
                "go through generator deployments + "
                "handle.options(stream=True) instead")
        queues = {}  # instance id -> _BatchQueue (methods) / None key (fns)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                instance, arg = args
                key = id(instance)
            elif len(args) == 1:
                instance, arg = None, args[0]
                key = None
            else:
                raise TypeError("@serve.batch methods take one argument")
            q = queues.get(key)
            if q is None:
                q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                queues[key] = q
            return await q.submit(instance, arg)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
