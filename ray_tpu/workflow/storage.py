"""Workflow storage: durable step results + workflow metadata.

Reference: python/ray/workflow/workflow_storage.py — filesystem layout
per workflow id: the pickled DAG, per-step results, and a status file.
Writes are atomic (tmp + rename) so a crash mid-write never leaves a
corrupt checkpoint.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from typing import Any, List, Optional

from ray_tpu.core import serialization as _ser


def default_storage_dir() -> str:
    return os.environ.get(
        "RAY_TPU_WORKFLOW_STORAGE",
        os.path.expanduser("~/ray_tpu_workflows"))


class WorkflowStorage:
    def __init__(self, workflow_id: str,
                 storage_dir: Optional[str] = None, *,
                 create: bool = False):
        self.workflow_id = workflow_id
        self.root = os.path.join(storage_dir or default_storage_dir(),
                                 workflow_id)
        self.steps_dir = os.path.join(self.root, "steps")
        if create:
            os.makedirs(self.steps_dir, exist_ok=True)

    def exists(self) -> bool:
        return os.path.isdir(self.root)

    def _atomic_write(self, path: str, data: bytes):
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    # -- DAG -----------------------------------------------------------
    def save_dag(self, dag) -> None:
        self._atomic_write(os.path.join(self.root, "dag.pkl"),
                           _ser.dumps_control(dag))

    def load_dag(self):
        with open(os.path.join(self.root, "dag.pkl"), "rb") as f:
            return _ser.loads_control(f.read())

    # -- steps ---------------------------------------------------------
    def _step_path(self, step_key: str) -> str:
        return os.path.join(self.steps_dir, f"{step_key}.pkl")

    def has_step(self, step_key: str) -> bool:
        return os.path.exists(self._step_path(step_key))

    def save_step(self, step_key: str, result: Any) -> None:
        self._atomic_write(self._step_path(step_key),
                           pickle.dumps(result))

    def load_step(self, step_key: str) -> Any:
        with open(self._step_path(step_key), "rb") as f:
            return pickle.load(f)

    # -- status --------------------------------------------------------
    def set_status(self, status: str, error: Optional[str] = None,
                   fingerprint: Optional[str] = None):
        payload = self.get_status()
        if payload.get("status") == "NOT_FOUND":
            payload = {}
        payload.update({"status": status, "error": error,
                        "ts": time.time()})
        if fingerprint is not None:
            payload["fingerprint"] = fingerprint
        self._atomic_write(os.path.join(self.root, "status.json"),
                           json.dumps(payload).encode())

    def get_status(self) -> dict:
        try:
            with open(os.path.join(self.root, "status.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {"status": "NOT_FOUND"}

    def save_output(self, value: Any):
        self._atomic_write(os.path.join(self.root, "output.pkl"),
                           pickle.dumps(value))

    def load_output(self) -> Any:
        with open(os.path.join(self.root, "output.pkl"), "rb") as f:
            return pickle.load(f)

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.root, "output.pkl"))

    def delete(self):
        shutil.rmtree(self.root, ignore_errors=True)


def list_workflow_ids(storage_dir: Optional[str] = None) -> List[str]:
    root = storage_dir or default_storage_dir()
    if not os.path.isdir(root):
        return []
    return sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)))
