"""ray_tpu.workflow — durable DAG execution.

Reference capability: python/ray/workflow (workflow.run, per-step
checkpoints in workflow_storage.py, replay recovery in
workflow_state_from_storage.py). A workflow is a DAG (ray_tpu.dag
nodes); each step's result is checkpointed to storage as it completes,
and resume replays the DAG with completed steps served from storage —
so a crashed workflow continues from its last finished step.
"""

from ray_tpu.workflow.api import (
    delete,
    get_output,
    get_status,
    list_all,
    resume,
    run,
    run_async,
)

__all__ = [
    "delete",
    "get_output",
    "get_status",
    "list_all",
    "resume",
    "run",
    "run_async",
]
