"""workflow.run / resume / status — the public workflow API.

Reference: python/ray/workflow/api.py + workflow_executor.py. Execution
is a ready-set scheduler over the DAG: independent branches run
concurrently as remote tasks, each step's result is checkpointed the
moment it completes and always before any dependent starts, and on
resume completed steps are served from storage (replay recovery).
"""

from __future__ import annotations

import hashlib
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core import serialization as _ser
from ray_tpu.dag import DAGNode, FunctionNode, InputNode, map_structure
from ray_tpu.workflow.storage import WorkflowStorage, list_workflow_ids


def _step_keys(root: DAGNode) -> Dict[int, str]:
    """Deterministic step keys: topo position + function name. Stable
    across resume because topo_order is a deterministic DFS of the same
    pickled DAG."""
    keys = {}
    for pos, node in enumerate(root.topo_order()):
        if isinstance(node, FunctionNode):
            keys[id(node)] = f"{pos:04d}_{node.name}"
    return keys


def _dag_fingerprint(dag: DAGNode) -> str:
    return hashlib.sha256(_ser.dumps_control(
        [(k, ) for k in sorted(_step_keys(dag).values())]
    )).hexdigest()[:16]


def _execute_workflow(root: DAGNode, storage: WorkflowStorage) -> Any:
    keys = _step_keys(root)
    results: Dict[int, Any] = {}

    def resolve_node(node: DAGNode):
        if isinstance(node, InputNode):
            raise ValueError("workflows take no runtime inputs; bind "
                             "constants into the DAG")
        return results[id(node)]

    storage.set_status("RUNNING")
    try:
        nodes = [n for n in root.topo_order()
                 if isinstance(n, FunctionNode)]
        remaining = {id(n): n for n in nodes}
        deps = {id(n): {id(c) for c in n._children()
                        if isinstance(c, FunctionNode)}
                for n in nodes}
        # Serve already-checkpointed steps from storage.
        for n in nodes:
            if storage.has_step(keys[id(n)]):
                results[id(n)] = storage.load_step(keys[id(n)])
                remaining.pop(id(n), None)
        inflight: Dict[Any, int] = {}  # ref -> node id
        while remaining or inflight:
            ready = [n for nid, n in remaining.items()
                     if deps[nid] <= results.keys() and not any(
                         ref_nid == nid for ref_nid in inflight.values())]
            for n in ready:
                args = tuple(map_structure(resolve_node, a)
                             for a in n.args)
                kwargs = {k: map_structure(resolve_node, v)
                          for k, v in n.kwargs.items()}
                inflight[n.remote_fn.remote(*args, **kwargs)] = id(n)
            if not inflight:
                raise RuntimeError("workflow deadlock (cyclic DAG?)")
            done, _ = ray_tpu.wait(list(inflight), num_returns=1,
                                   timeout=None)
            ref = done[0]
            nid = inflight.pop(ref)
            value = ray_tpu.get(ref)
            # Checkpoint BEFORE any dependent can start: the durability
            # contract is that a step never re-executes once recorded.
            storage.save_step(keys[nid], value)
            results[nid] = value
            remaining.pop(nid, None)
        output = results[id(root)]
        storage.save_output(output)
        storage.set_status("SUCCESSFUL")
        return output
    except BaseException as e:
        storage.set_status("FAILED", error=str(e))
        raise


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        storage_dir: Optional[str] = None) -> Any:
    """Run a workflow to completion, checkpointing each step."""
    if not isinstance(dag, DAGNode):
        raise TypeError("workflow.run takes a DAG (use fn.bind(...))")
    workflow_id = workflow_id or f"workflow_{uuid.uuid4().hex[:12]}"
    storage = WorkflowStorage(workflow_id, storage_dir, create=True)
    fingerprint = _dag_fingerprint(dag)
    recorded = storage.get_status().get("fingerprint")
    if recorded is not None and recorded != fingerprint:
        raise ValueError(
            f"workflow id {workflow_id!r} was already used for a "
            f"different DAG; delete it or pick a new id")
    if storage.has_output():
        return storage.load_output()  # idempotent re-run, same DAG
    storage.save_dag(dag)
    storage.set_status("PENDING", fingerprint=fingerprint)
    return _execute_workflow(dag, storage)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              storage_dir: Optional[str] = None):
    """Run a workflow in a detached driver task; returns (workflow_id,
    ObjectRef of the output)."""
    workflow_id = workflow_id or f"workflow_{uuid.uuid4().hex[:12]}"
    storage = WorkflowStorage(workflow_id, storage_dir, create=True)
    storage.save_dag(dag)
    storage.set_status("PENDING", fingerprint=_dag_fingerprint(dag))

    @ray_tpu.remote
    def _driver(wf_id: str, sdir):
        from ray_tpu.workflow.api import resume

        return resume(wf_id, storage_dir=sdir)

    return workflow_id, _driver.options(num_cpus=0.1).remote(
        workflow_id, storage_dir)


def resume(workflow_id: str, *, storage_dir: Optional[str] = None) -> Any:
    """Resume an interrupted workflow: completed steps replay from
    storage, the rest execute."""
    storage = WorkflowStorage(workflow_id, storage_dir)
    if not storage.exists():
        raise ValueError(f"no workflow {workflow_id!r}")
    if storage.has_output():
        return storage.load_output()
    dag = storage.load_dag()
    return _execute_workflow(dag, storage)


def get_status(workflow_id: str, *,
               storage_dir: Optional[str] = None) -> str:
    return WorkflowStorage(workflow_id, storage_dir).get_status()["status"]


def get_output(workflow_id: str, *,
               storage_dir: Optional[str] = None) -> Any:
    storage = WorkflowStorage(workflow_id, storage_dir)
    if not storage.has_output():
        raise ValueError(f"workflow {workflow_id} has no output "
                         f"(status={storage.get_status()['status']})")
    return storage.load_output()


def list_all(storage_dir: Optional[str] = None) -> List[tuple]:
    out = []
    for wf_id in list_workflow_ids(storage_dir):
        status = WorkflowStorage(wf_id, storage_dir).get_status()
        out.append((wf_id, status["status"]))
    return out


def delete(workflow_id: str, *, storage_dir: Optional[str] = None):
    WorkflowStorage(workflow_id, storage_dir).delete()
