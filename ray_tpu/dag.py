"""DAG nodes: lazy task graphs built with .bind().

Reference: python/ray/dag/ (dag_node.py, function_node.py,
input_node.py) — ``fn.bind(*args)`` records a node instead of
executing; ``node.execute()`` walks the graph submitting tasks whose
arguments are upstream ObjectRefs, so the whole DAG runs without
materializing intermediates on the driver. This is also the workflow
library's substrate (per-step durable execution). Nodes nested inside
lists/tuples/dicts are found and resolved like top-level arguments.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List

_node_counter = itertools.count()


def map_structure(fn: Callable[[Any], Any], value: Any) -> Any:
    """Apply fn to DAGNodes anywhere inside lists/tuples/dicts."""
    if isinstance(value, DAGNode):
        return fn(value)
    if isinstance(value, list):
        return [map_structure(fn, v) for v in value]
    if isinstance(value, tuple):
        return tuple(map_structure(fn, v) for v in value)
    if isinstance(value, dict):
        return {k: map_structure(fn, v) for k, v in value.items()}
    return value


def find_nodes(value: Any, out: List["DAGNode"]) -> None:
    if isinstance(value, DAGNode):
        out.append(value)
    elif isinstance(value, (list, tuple)):
        for v in value:
            find_nodes(v, out)
    elif isinstance(value, dict):
        for v in value.values():
            find_nodes(v, out)


class DAGNode:
    def execute(self, *input_args, **input_kwargs):
        return _ExecutionState(input_args, input_kwargs).submit(self)

    def experimental_compile(self, buffer_size_bytes: int = 1 << 20,
                             max_inflight: int = 8):
        """Compile this DAG onto pre-allocated shm channels with pinned
        actor loops (reference: dag.experimental_compile,
        compiled_dag_node.py:19). Returns a CompiledDag whose
        ``execute()`` skips the task path entirely."""
        from ray_tpu.experimental.compiled_dag import CompiledDag

        return CompiledDag(self, buffer_size_bytes=buffer_size_bytes,
                           max_inflight=max_inflight)

    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []
        for a in list(self.args) + list(self.kwargs.values()):
            find_nodes(a, out)
        return out

    def topo_order(self) -> List["DAGNode"]:
        """Deterministic post-order (children before parents)."""
        seen = set()
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            for c in node._children():
                visit(c)
            order.append(node)

        visit(self)
        return order


class InputNode(DAGNode):
    """Placeholder for the value passed to execute() (reference:
    input_node.py). Supports a single positional input."""

    def __init__(self):
        self.args = ()
        self.kwargs = {}
        self.index = next(_node_counter)

    def __repr__(self):
        return "InputNode()"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs
        self.index = next(_node_counter)

    @property
    def name(self) -> str:
        fn = getattr(self.remote_fn, "_fn", None)
        return getattr(fn, "__name__", "fn")

    def __repr__(self):
        return f"FunctionNode({self.name})"


class ClassMethodNode(DAGNode):
    """Lazy actor-method call (reference: dag/class_node.py's
    ClassMethodNode) — the node type the compiled-DAG path pins into
    channel loops."""

    def __init__(self, actor_handle, method_name: str, args: tuple,
                 kwargs: dict):
        self.actor_handle = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        self.index = next(_node_counter)

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"


class _ExecutionState:
    def __init__(self, input_args: tuple, input_kwargs: dict):
        if input_kwargs:
            raise TypeError(
                "execute() takes a single positional input; keyword "
                "inputs are not supported")
        self.input_args = input_args
        self.results: Dict[int, Any] = {}

    def _resolve_node(self, node: "DAGNode", materialize: bool):
        if isinstance(node, InputNode):
            if not self.input_args:
                raise ValueError(
                    "DAG contains an InputNode but execute() was called "
                    "without an input")
            return self.input_args[0]
        ref = self.results[id(node)]
        if materialize:
            # Refs nested inside containers are not dereferenced by the
            # worker (matching top-level-only arg resolution), so nested
            # node results must be materialized here.
            import ray_tpu

            return ray_tpu.get(ref)
        return ref

    def resolve(self, value):
        if isinstance(value, DAGNode):
            return self._resolve_node(value, materialize=False)
        return map_structure(
            lambda n: self._resolve_node(n, materialize=True), value)

    def submit(self, root: DAGNode):
        for node in root.topo_order():
            if isinstance(node, InputNode):
                continue
            args = tuple(self.resolve(a) for a in node.args)
            kwargs = {k: self.resolve(v) for k, v in node.kwargs.items()}
            if isinstance(node, ClassMethodNode):
                method = getattr(node.actor_handle, node.method_name)
                self.results[id(node)] = method.remote(*args, **kwargs)
            else:
                self.results[id(node)] = node.remote_fn.remote(
                    *args, **kwargs)
        return self.results[id(root)]
