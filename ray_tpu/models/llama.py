"""Llama-family transformer (flagship model), TPU-first.

Design notes (per BASELINE.json north star — Llama-2-7B GSPMD FSDP):
- bfloat16 activations/params by default; fp32 RMSNorm statistics and
  softmax (MXU-friendly, VPU for the rest).
- GQA attention through ``ray_tpu.ops.attention`` (Pallas flash kernel on
  TPU) or a sequence-parallel callable (ring/Ulysses from
  ``ray_tpu.parallel.ring_attention``).
- every parameter annotated with logical axes via
  ``nn.with_logical_partitioning`` so dp/fsdp/tp/sp/ep are rule-table
  swaps (see ray_tpu/parallel/sharding.py LOGICAL_RULES).
- optional layer scan + remat (`config.scan_layers`,
  `config.remat`) to trade FLOPs for HBM.
- optional MoE MLP with top-k routing on an "expert" logical axis.

The reference framework contains no model zoo for LLMs (RLlib models are
RL policy nets); this is the TPU-native flagship required by the survey's
build plan §7.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention as default_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # default hidden_size // num_heads
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 4096
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    # "full": recompute everything (min HBM); "dots": save matmul
    # outputs and recompute only cheap elementwise ops (the
    # MaxText-style minimal policy — much higher MFU at modest HBM
    # cost). Ignored when remat=False.
    remat_policy: str = "full"

    def __post_init__(self):
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', "
                f"got {self.remat_policy!r}")
    # MoE (0 experts = dense MLP)
    num_experts: int = 0
    num_experts_per_token: int = 2
    # attention implementation: "auto" | "flash" | "xla"
    attention_impl: str = "auto"

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        base = dict(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256,
            scan_layers=False, remat=False,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**overrides) -> "LlamaConfig":
        base = dict(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_layers=32, num_heads=32, num_kv_heads=32, max_seq_len=4096,
        )
        base.update(overrides)
        return LlamaConfig(**base)

    def num_params(self) -> int:
        h, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        dh = self.resolved_head_dim
        attn = h * (self.num_heads * dh) * 2 + h * (self.num_kv_heads * dh) * 2
        if self.num_experts > 0:
            mlp = 3 * h * f * self.num_experts + h * self.num_experts
        else:
            mlp = 3 * h * f
        per_layer = attn + mlp + 2 * h
        return self.num_layers * per_layer + 2 * v * h + h


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],),
            jnp.float32,
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        normed = x32 * jax.lax.rsqrt(var + self.eps)
        return (normed * scale).astype(self.dtype)


def _rope(x, positions, theta: float):
    """Rotary embedding over the last dim (x: ..., seq, heads, head_dim)."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (..., S, half)
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _dense(features, name, kernel_axes, dtype, param_dtype):
    return nn.Dense(
        features,
        use_bias=False,
        name=name,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), kernel_axes
        ),
    )


class Attention(nn.Module):
    config: LlamaConfig
    # Injected attention callable (e.g. ring attention); None = default.
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        dh = cfg.resolved_head_dim
        wq = _dense(cfg.num_heads * dh, "wq", ("embed", "heads"),
                    cfg.dtype, cfg.param_dtype)
        wk = _dense(cfg.num_kv_heads * dh, "wk", ("embed", "kv_heads"),
                    cfg.dtype, cfg.param_dtype)
        wv = _dense(cfg.num_kv_heads * dh, "wv", ("embed", "kv_heads"),
                    cfg.dtype, cfg.param_dtype)
        wo = _dense(cfg.hidden_size, "wo", ("heads", "embed"),
                    cfg.dtype, cfg.param_dtype)
        B, S, _ = x.shape
        q = wq(x).reshape(B, S, cfg.num_heads, dh)
        k = wk(x).reshape(B, S, cfg.num_kv_heads, dh)
        v = wv(x).reshape(B, S, cfg.num_kv_heads, dh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v)
        else:
            out = default_attention(q, k, v, causal=True,
                                    impl=cfg.attention_impl)
        out = out.reshape(B, S, cfg.num_heads * dh)
        return wo(out)


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        gate = _dense(cfg.intermediate_size, "gate", ("embed", "ffn"),
                      cfg.dtype, cfg.param_dtype)
        up = _dense(cfg.intermediate_size, "up", ("embed", "ffn"),
                    cfg.dtype, cfg.param_dtype)
        down = _dense(cfg.hidden_size, "down", ("ffn", "embed"),
                      cfg.dtype, cfg.param_dtype)
        return down(nn.silu(gate(x)) * up(x))


class MoEMLP(nn.Module):
    """Top-k routed mixture of experts with an expert-parallel axis.

    Dispatch uses dense one-hot combines (capacity-free). Expert weights
    carry the "expert" logical axis; with an `expert` mesh axis the einsum
    becomes an all-to-all-free sharded computation under GSPMD.
    """

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        E, K = cfg.num_experts, cfg.num_experts_per_token
        H, F = cfg.hidden_size, cfg.intermediate_size
        B, S, _ = x.shape
        router = _dense(E, "router", ("embed", None),
                        jnp.float32, cfg.param_dtype)
        logits = router(x.astype(jnp.float32))  # (B,S,E)
        weights, idx = jax.lax.top_k(jax.nn.softmax(logits, axis=-1), K)
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
        # one-hot combine: (B,S,K,E)
        dispatch = jax.nn.one_hot(idx, E, dtype=cfg.dtype)
        combine = dispatch * weights[..., None].astype(cfg.dtype)

        def ew(name, shape, axes):
            return self.param(
                name,
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), axes
                ),
                shape, cfg.param_dtype,
            ).astype(cfg.dtype)

        w_gate = ew("w_gate", (E, H, F), ("expert", "embed", "expert_ffn"))
        w_up = ew("w_up", (E, H, F), ("expert", "embed", "expert_ffn"))
        w_down = ew("w_down", (E, F, H), ("expert", "expert_ffn", "embed"))
        # tokens routed to experts: (E, B, S, H)
        xin = jnp.einsum("bske,bsh->ebsh", combine, x)
        h = nn.silu(jnp.einsum("ebsh,ehf->ebsf", xin, w_gate))
        h = h * jnp.einsum("ebsh,ehf->ebsf", xin, w_up)
        out = jnp.einsum("ebsf,efh->ebsh", h, w_down)
        return jnp.einsum("ebsh,bske->bsh", out, combine).astype(cfg.dtype)


class Block(nn.Module):
    config: LlamaConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        h = x + Attention(cfg, self.attention_fn, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="attn_norm")(x),
            positions,
        )
        mlp_cls = MoEMLP if cfg.num_experts > 0 else MLP
        out = h + mlp_cls(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="mlp_norm")(h)
        )
        return out


class Llama(nn.Module):
    config: LlamaConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens):
        cfg = self.config
        B, S = tokens.shape
        embed = self.param(
            "embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                ("vocab_shard", "embed"),
            ),
            (cfg.vocab_size, cfg.hidden_size),
            cfg.param_dtype,
        )
        x = embed[tokens].astype(cfg.dtype)
        positions = jnp.arange(S)[None, :].repeat(B, axis=0)

        block = Block
        if cfg.remat:
            policy = None
            if cfg.remat_policy == "dots":
                policy = (jax.checkpoint_policies
                          .dots_with_no_batch_dims_saveable)
            block = nn.remat(
                Block, prevent_cse=not cfg.scan_layers,
                static_argnums=(), policy=policy,
            )
        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (mdl(carry, positions), None),
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block(cfg, self.attention_fn, name="layers"), x, None)
        else:
            for i in range(cfg.num_layers):
                x = block(cfg, self.attention_fn, name=f"layer_{i}")(
                    x, positions
                )
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="final_norm")(x)
        lm_head = _dense(cfg.vocab_size, "lm_head",
                         ("embed", "vocab_shard"), cfg.dtype,
                         cfg.param_dtype)
        return lm_head(x)


def cross_entropy_loss(logits, targets, ignore_index: int = -100):
    mask = (targets != ignore_index)
    safe_targets = jnp.where(mask, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    token_losses = -jnp.take_along_axis(
        logp, safe_targets[..., None], axis=-1
    ).squeeze(-1)
    token_losses = jnp.where(mask, token_losses, 0.0)
    return jnp.sum(token_losses) / jnp.maximum(jnp.sum(mask), 1)
