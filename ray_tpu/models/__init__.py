from ray_tpu.models.llama import Llama, LlamaConfig
from ray_tpu.models.mlp import MLP

__all__ = ["Llama", "LlamaConfig", "MLP"]
