"""Small MLP for the MNIST end-to-end slice (SURVEY.md §7.2 /
BASELINE.json config #2: "Ray Train MNIST JaxTrainer")."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 64, 10)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features[:-1]):
            x = nn.relu(nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x))
        return nn.Dense(self.features[-1], dtype=self.dtype,
                        name="out")(x)
