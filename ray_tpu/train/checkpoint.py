"""Checkpoint: a directory handle + sharded-pytree save/restore.

Reference surface: python/ray/train/_checkpoint.py:56 (Checkpoint as a
directory reference with from_directory/to_directory/as_directory) and the
orbax-style TPU mapping from SURVEY.md §5.4: every host writes its own
shard of a sharded jax pytree; restore re-shards onto the running mesh.

Pytree persistence uses flax.serialization msgpack for leaves plus a
pickled treedef skeleton — no framework lock-in in the directory format:
``checkpoint_dir/{shard_<rank>.msgpack, meta.pkl, COMMIT, <user files>}``.

Crash consistency (orbax-style atomic save): every file lands via
temp-name + ``os.replace`` + fsync, ``meta.pkl`` strictly before any
shard, and rank 0 writes a ``COMMIT`` marker last — a JSON record of the
expected shard set (with byte sizes where known). Readers that honor the
marker (CheckpointManager.register / recover_from_dir) never see a torn
directory: no marker, a listed shard missing, or a size mismatch all
mean the writer crashed mid-save.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Iterator, Optional

#: Commit-marker file name. Present + consistent == the directory is a
#: complete checkpoint; anything else is torn and must not be resumed.
COMMIT_MARKER = "COMMIT"


def _fsync_dir(path: str) -> None:
    """Durably record directory-entry renames (POSIX: the rename itself
    is atomic but not durable until the directory is fsynced)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """Write ``data`` so readers see either nothing or all of it: temp
    name in the same directory, fsync, ``os.replace``, dir fsync."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


class Checkpoint:
    """A handle to a checkpoint directory (local/shared filesystem)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        """Materialize into ``dest`` (copy). Reference semantics: always a
        private copy the caller may mutate."""
        dest = dest or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Read-only view without copying (we are on a shared fs)."""
        yield self.path

    # -- pytree helpers ----------------------------------------------------

    @classmethod
    def from_pytree(cls, tree: Any, path: str, *,
                    shard_rank: Optional[int] = None,
                    world_size: Optional[int] = None,
                    user_meta: Optional[dict] = None) -> "Checkpoint":
        """Write ``tree`` (host-local arrays or a process's addressable
        shards) as this rank's shard file. Multi-host: every rank calls
        this with the same ``path`` on shared storage.

        ``shard_rank`` defaults to the calling worker's world rank when
        a train session is active (so concurrent ranks never clobber
        each other's shard file), else 0.

        Write order is crash-safe: ``meta.pkl`` first, then the shard,
        each atomically — a reader can never see a shard without its
        treedef metadata. Rank 0 commits last: the ``COMMIT`` marker
        records the shards this writer itself guarantees (its own, with
        exact size — so a rank-0-only replicated save is complete and
        registrable by itself), plus the full ``shard_0..world_size-1``
        set as existence-only expectations when ``world_size`` is
        passed explicitly. Peer shards a direct shared-path caller did
        not declare are unprotected until the trainer's gang-commit
        rewrites the marker from the merged shard set (which it does
        only after every rank reported)."""
        import jax
        from flax import serialization

        if shard_rank is None:
            from ray_tpu.train import session as _session_mod

            active = _session_mod._session
            shard_rank = active.context.world_rank if active else 0
        os.makedirs(path, exist_ok=True)
        # Pull addressable data to host; fully-replicated arrays write only
        # from rank 0 (callers pass shard_rank=their process index).
        host_tree = jax.tree.map(_to_host, tree)
        leaves, treedef = jax.tree.flatten(host_tree)
        blob = serialization.msgpack_serialize(
            {str(i): leaf for i, leaf in enumerate(leaves)})
        shard_name = f"shard_{shard_rank}.msgpack"
        if shard_rank == 0:
            meta_blob = pickle.dumps({"treedef": treedef,
                                      "user_meta": user_meta or {}})
            _atomic_write(os.path.join(path, "meta.pkl"), meta_blob)
        _atomic_write(os.path.join(path, shard_name), blob)
        ckpt = cls(path)
        if shard_rank == 0:
            shards: Dict[str, Optional[int]] = {
                f"shard_{r}.msgpack": None
                for r in range(world_size or 0)}
            shards[shard_name] = len(blob)
            ckpt.commit(shards=shards)
        return ckpt

    # -- commit marker -----------------------------------------------------

    def commit(self, shards: Optional[Dict[str, Optional[int]]] = None,
               extra: Optional[dict] = None) -> None:
        """Write the ``COMMIT`` marker (last, fsynced). ``shards`` maps
        shard file name -> expected byte size (None = existence-only);
        defaults to the sizes of the shard files currently on disk."""
        if shards is None:
            shards = {
                name: os.path.getsize(os.path.join(self.path, name))
                for name in self.shard_files()
            }
        record = {
            "shards": shards,
            "has_meta": os.path.exists(os.path.join(self.path, "meta.pkl")),
        }
        if extra:
            record.update(extra)
        _atomic_write(os.path.join(self.path, COMMIT_MARKER),
                      json.dumps(record, sort_keys=True).encode())

    def commit_info(self) -> Optional[dict]:
        """The parsed COMMIT marker, or None when absent/unreadable."""
        try:
            with open(os.path.join(self.path, COMMIT_MARKER), "rb") as f:
                return json.loads(f.read().decode())
        except (OSError, ValueError):
            return None

    def validate_committed(self) -> Optional[str]:
        """None when this directory is a complete, committed checkpoint;
        otherwise a human-readable torn-ness reason. Directories holding
        neither a marker nor shard files (opaque user checkpoints) pass —
        there is nothing to validate."""
        info = self.commit_info()
        if info is None:
            if os.path.exists(os.path.join(self.path, COMMIT_MARKER)):
                return "unreadable COMMIT marker"
            if self.shard_files():
                return "shard files present but no COMMIT marker"
            return None
        for name, size in (info.get("shards") or {}).items():
            full = os.path.join(self.path, name)
            if not os.path.exists(full):
                return f"missing shard {name}"
            if size is not None and os.path.getsize(full) != size:
                return (f"truncated shard {name} "
                        f"({os.path.getsize(full)} != {size} bytes)")
        if info.get("has_meta") and not os.path.exists(
                os.path.join(self.path, "meta.pkl")):
            return "missing meta.pkl"
        return None

    def to_pytree(self, *, shard_rank: Optional[int] = None) -> Any:
        """Restore this rank's shard as a pytree of numpy arrays; callers
        re-shard onto their mesh with jax.device_put(..., sharding).

        ``shard_rank`` defaults to the calling worker's world rank when a
        train session is active — symmetric with ``from_pytree``, so a
        rank>0 worker resuming from a per-rank sharded checkpoint gets its
        own shard, not rank 0's."""
        import jax
        from flax import serialization

        if shard_rank is None:
            from ray_tpu.train import session as _session_mod

            active = _session_mod._session
            shard_rank = active.context.world_rank if active else 0

        with open(os.path.join(self.path, "meta.pkl"), "rb") as f:
            meta = pickle.load(f)
        shard_file = os.path.join(self.path,
                                  f"shard_{shard_rank}.msgpack")
        if not os.path.exists(shard_file):
            # A single-shard (replicated) checkpoint restores on any rank.
            # But if other per-rank shards exist, a missing one means real
            # data loss — never silently substitute another rank's data.
            shards = self.shard_files()
            if shards == ["shard_0.msgpack"]:
                shard_file = os.path.join(self.path, "shard_0.msgpack")
            else:
                raise FileNotFoundError(
                    f"checkpoint {self.path} has no shard for rank "
                    f"{shard_rank} (found: {sorted(shards)})"
                )
        with open(shard_file, "rb") as f:
            loaded = serialization.msgpack_restore(f.read())
        leaves = [loaded[str(i)] for i in range(len(loaded))]
        return jax.tree.unflatten(meta["treedef"], leaves)

    def shard_files(self) -> list:
        """Names of per-rank shard files in this checkpoint."""
        return sorted(f for f in os.listdir(self.path)
                      if f.startswith("shard_") and f.endswith(".msgpack"))

    @property
    def user_meta(self) -> dict:
        with open(os.path.join(self.path, "meta.pkl"), "rb") as f:
            return pickle.load(f)["user_meta"]

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint({self.path})"


def _to_host(x):
    import jax
    import numpy as np

    if isinstance(x, jax.Array):
        if not x.is_fully_addressable:
            # Multi-host sharded array: persist only this process's shards
            # (orbax recipe); restore stitches by re-sharding.
            return np.stack([s.data for s in x.addressable_shards])
        return np.asarray(x)
    return x
