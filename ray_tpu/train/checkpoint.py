"""Checkpoint: a directory handle + sharded-pytree save/restore.

Reference surface: python/ray/train/_checkpoint.py:56 (Checkpoint as a
directory reference with from_directory/to_directory/as_directory) and the
orbax-style TPU mapping from SURVEY.md §5.4: every host writes its own
shard of a sharded jax pytree; restore re-shards onto the running mesh.

Pytree persistence uses flax.serialization msgpack for leaves plus a
pickled treedef skeleton — no framework lock-in in the directory format:
``checkpoint_dir/{shard_<rank>.msgpack, meta.pkl, <user files>}``.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import shutil
import tempfile
from typing import Any, Iterator, Optional


class Checkpoint:
    """A handle to a checkpoint directory (local/shared filesystem)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        """Materialize into ``dest`` (copy). Reference semantics: always a
        private copy the caller may mutate."""
        dest = dest or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Read-only view without copying (we are on a shared fs)."""
        yield self.path

    # -- pytree helpers ----------------------------------------------------

    @classmethod
    def from_pytree(cls, tree: Any, path: str, *,
                    shard_rank: Optional[int] = None,
                    user_meta: Optional[dict] = None) -> "Checkpoint":
        """Write ``tree`` (host-local arrays or a process's addressable
        shards) as this rank's shard file. Multi-host: every rank calls
        this with the same ``path`` on shared storage.

        ``shard_rank`` defaults to the calling worker's world rank when a
        train session is active (so concurrent ranks never clobber each
        other's shard file), else 0."""
        import jax
        from flax import serialization

        if shard_rank is None:
            from ray_tpu.train import session as _session_mod

            active = _session_mod._session
            shard_rank = active.context.world_rank if active else 0
        os.makedirs(path, exist_ok=True)
        # Pull addressable data to host; fully-replicated arrays write only
        # from rank 0 (callers pass shard_rank=their process index).
        host_tree = jax.tree.map(_to_host, tree)
        leaves, treedef = jax.tree.flatten(host_tree)
        blob = serialization.msgpack_serialize(
            {str(i): leaf for i, leaf in enumerate(leaves)})
        with open(os.path.join(path, f"shard_{shard_rank}.msgpack"),
                  "wb") as f:
            f.write(blob)
        if shard_rank == 0:
            with open(os.path.join(path, "meta.pkl"), "wb") as f:
                pickle.dump({"treedef": treedef,
                             "user_meta": user_meta or {}}, f)
        return cls(path)

    def to_pytree(self, *, shard_rank: Optional[int] = None) -> Any:
        """Restore this rank's shard as a pytree of numpy arrays; callers
        re-shard onto their mesh with jax.device_put(..., sharding).

        ``shard_rank`` defaults to the calling worker's world rank when a
        train session is active — symmetric with ``from_pytree``, so a
        rank>0 worker resuming from a per-rank sharded checkpoint gets its
        own shard, not rank 0's."""
        import jax
        from flax import serialization

        if shard_rank is None:
            from ray_tpu.train import session as _session_mod

            active = _session_mod._session
            shard_rank = active.context.world_rank if active else 0

        with open(os.path.join(self.path, "meta.pkl"), "rb") as f:
            meta = pickle.load(f)
        shard_file = os.path.join(self.path,
                                  f"shard_{shard_rank}.msgpack")
        if not os.path.exists(shard_file):
            # A single-shard (replicated) checkpoint restores on any rank.
            # But if other per-rank shards exist, a missing one means real
            # data loss — never silently substitute another rank's data.
            shards = self.shard_files()
            if shards == ["shard_0.msgpack"]:
                shard_file = os.path.join(self.path, "shard_0.msgpack")
            else:
                raise FileNotFoundError(
                    f"checkpoint {self.path} has no shard for rank "
                    f"{shard_rank} (found: {sorted(shards)})"
                )
        with open(shard_file, "rb") as f:
            loaded = serialization.msgpack_restore(f.read())
        leaves = [loaded[str(i)] for i in range(len(loaded))]
        return jax.tree.unflatten(meta["treedef"], leaves)

    def shard_files(self) -> list:
        """Names of per-rank shard files in this checkpoint."""
        return sorted(f for f in os.listdir(self.path)
                      if f.startswith("shard_") and f.endswith(".msgpack"))

    @property
    def user_meta(self) -> dict:
        with open(os.path.join(self.path, "meta.pkl"), "rb") as f:
            return pickle.load(f)["user_meta"]

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint({self.path})"


def _to_host(x):
    import jax
    import numpy as np

    if isinstance(x, jax.Array):
        if not x.is_fully_addressable:
            # Multi-host sharded array: persist only this process's shards
            # (orbax recipe); restore stitches by re-sharding.
            return np.stack([s.data for s in x.addressable_shards])
        return np.asarray(x)
    return x
