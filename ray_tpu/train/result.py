"""Result of a training run (reference: python/ray/air/result.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[str] = None
    metrics_history: Optional[List[Dict[str, Any]]] = None
    best_checkpoint: Optional[Checkpoint] = None

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.metrics_history or [])
