"""Run/scaling/failure/checkpoint configuration dataclasses.

Reference surface: python/ray/air/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig). TPU-first deltas: ``ScalingConfig`` gains
a ``topology`` field describing the pod slice (one worker actor per TPU
host, gang-placed via a placement group over the slice-head resource —
reference accelerator trick: _private/accelerators/tpu.py:335), and a
``mesh_shape`` preset handed to the JaxBackend for GSPMD.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    On a TPU pod slice: ``num_workers`` = number of hosts, each worker
    claims the host's chips (``tpus_per_worker``); jax.distributed makes
    the slice one device world.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: float = 0.0
    cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    topology: Optional[str] = None  # e.g. "v5e-16": gang over slice heads
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", float(self.cpus_per_worker))
        if self.use_tpu or self.tpus_per_worker:
            res.setdefault("TPU", float(self.tpus_per_worker or 1.0))
        return res

    @property
    def total_workers(self) -> int:
        return int(self.num_workers)


@dataclasses.dataclass
class FailureConfig:
    """Elastic-recovery policy (reference: air/config.py FailureConfig).
    ``max_failures``: group restarts (from latest checkpoint) before the
    run errors out; TPU note — a slice failure is a gang failure, the
    whole worker group restarts.

    Gang health monitoring: the BackendExecutor polls every rank's
    liveness and progress every ``health_check_interval_s`` seconds,
    independently of the report cadence. A rank whose actor died aborts
    the gang immediately; a rank whose train loop made no progress
    (no ``train.report`` / activity) for ``hang_timeout_s`` is declared
    hung — set ``hang_timeout_s`` above the longest legitimate gap
    between reports (first-step jit compiles included). ``None``
    disables hang detection; ``health_check_interval_s=0`` disables the
    monitor entirely (back to report-timeout-only detection).

    Elastic restart: between gang restarts the trainer backs off
    exponentially starting at ``restart_backoff_s``; it waits up to
    ``resource_wait_timeout_s`` for the full-size placement group to
    become placeable and, when the dead node's resources never return,
    may re-form a smaller gang down to ``min_workers`` (datasets are
    re-sharded for the new world size). ``min_workers=None`` pins the
    gang at its configured size (no shrink)."""

    max_failures: int = 0
    restart_backoff_s: float = 1.0
    resource_wait_timeout_s: float = 60.0
    min_workers: Optional[int] = None
    health_check_interval_s: float = 2.0
    hang_timeout_s: Optional[float] = 300.0


@dataclasses.dataclass
class CheckpointConfig:
    """Top-K retention (reference: air/config.py CheckpointConfig)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    """Where results/checkpoints land + failure policy
    (reference: air/config.py RunConfig)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    #: Resume from committed checkpoints already in the experiment dir
    #: (driver crash recovery). Default on: an unnamed run gets a
    #: timestamped dir, so this only triggers when the caller reuses a
    #: ``name`` — set False for a deliberate from-scratch rerun under
    #: the same name.
    auto_resume: bool = True
    verbose: int = 0
    # Tune stop criteria: {"metric": threshold, "training_iteration": N}
    # or a callable (trial_id, result) -> bool (reference: RunConfig.stop).
    stop: Optional[object] = None
    # Tune experiment callbacks (reference: air RunConfig.callbacks —
    # tune/callback.py Callback subclasses, incl. the CSV/JSON/TBX
    # logger callbacks).
    callbacks: Optional[list] = None

    def resolved_storage_path(self) -> str:
        return os.path.expanduser(
            self.storage_path or "~/ray_tpu_results")
