"""Top-K checkpoint retention keyed on a reported metric.

Reference: python/ray/train/_internal/checkpoint_manager.py (keep best K
by score attribute, always keep the latest).
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional, Tuple

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        # (score, index, checkpoint); score None when no attribute set
        self._tracked: List[Tuple[Optional[float], int, Checkpoint]] = []
        self._index = 0
        self.latest: Optional[Checkpoint] = None
        self.best: Optional[Checkpoint] = None

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[dict] = None) -> None:
        attr = self.config.checkpoint_score_attribute
        score = None
        if attr and metrics and attr in metrics:
            score = float(metrics[attr])
        self._tracked.append((score, self._index, checkpoint))
        self._index += 1
        self.latest = checkpoint
        self._update_best()
        self._evict()

    def _sort_key(self, entry):
        score, idx, _ = entry
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        # Unscored checkpoints rank by recency below any scored one.
        return (score is not None, sign * score if score is not None else idx)

    def _update_best(self):
        if self._tracked:
            self.best = max(self._tracked, key=self._sort_key)[2]

    def _evict(self):
        keep = self.config.num_to_keep
        if keep is None or len(self._tracked) <= keep:
            return
        ranked = sorted(self._tracked, key=self._sort_key, reverse=True)
        survivors = ranked[:keep]
        # Never evict the latest (resume anchor, reference behavior): it is
        # retained in addition to the top-K when it didn't make the cut.
        if self.latest is not None and all(
                c is not self.latest for _, _, c in survivors):
            survivors.append(next(
                e for e in self._tracked if e[2] is self.latest))
        doomed = [e for e in self._tracked if e not in survivors]
        self._tracked = [e for e in self._tracked if e in survivors]
        for _, _, ckpt in doomed:
            if os.path.isdir(ckpt.path):
                shutil.rmtree(ckpt.path, ignore_errors=True)
        # Best must point at a directory that still exists.
        self._update_best()
