"""Top-K checkpoint retention keyed on a reported metric.

Reference: python/ray/train/_internal/checkpoint_manager.py (keep best K
by score attribute, always keep the latest) + orbax-style commit
discipline: only directories whose ``COMMIT`` marker validates are ever
tracked, and ``recover_from_dir`` rebuilds the top-K state from disk
after a driver restart, skipping torn directories instead of resuming
from them.
"""

from __future__ import annotations

import logging
import os
import re
import shutil
from typing import List, Optional, Tuple

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig

logger = logging.getLogger(__name__)

_CKPT_DIR_RE = re.compile(r"^checkpoint_(\d+)$")


class TornCheckpointError(ValueError):
    """The directory is not a complete committed checkpoint."""


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        # (score, index, checkpoint); score None when no attribute set
        self._tracked: List[Tuple[Optional[float], int, Checkpoint]] = []
        self._index = 0
        self.latest: Optional[Checkpoint] = None
        self.best: Optional[Checkpoint] = None

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[dict] = None) -> None:
        torn = checkpoint.validate_committed()
        if torn is not None:
            raise TornCheckpointError(
                f"refusing to track torn checkpoint {checkpoint.path}: "
                f"{torn}")
        attr = self.config.checkpoint_score_attribute
        score = None
        if attr and metrics and attr in metrics:
            score = float(metrics[attr])
        self._tracked.append((score, self._index, checkpoint))
        self._index += 1
        self.latest = checkpoint
        self._update_best()
        self._evict()

    def _sort_key(self, entry):
        score, idx, _ = entry
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        # Unscored checkpoints rank by recency below any scored one.
        return (score is not None, sign * score if score is not None else idx)

    def _update_best(self):
        if self._tracked:
            self.best = max(self._tracked, key=self._sort_key)[2]

    def _evict(self):
        keep = self.config.num_to_keep
        if keep is None or len(self._tracked) <= keep:
            return
        ranked = sorted(self._tracked, key=self._sort_key, reverse=True)
        survivors = ranked[:keep]
        # Never evict the latest (resume anchor, reference behavior): it is
        # retained in addition to the top-K when it didn't make the cut.
        if self.latest is not None and all(
                c is not self.latest for _, _, c in survivors):
            survivors.append(next(
                e for e in self._tracked if e[2] is self.latest))
        doomed = [e for e in self._tracked if e not in survivors]
        self._tracked = [e for e in self._tracked if e in survivors]
        for _, _, ckpt in doomed:
            if os.path.isdir(ckpt.path):
                shutil.rmtree(ckpt.path, ignore_errors=True)
        # Best must point at a directory that still exists.
        self._update_best()

    # -- crash recovery ----------------------------------------------------

    def recover_from_dir(self, exp_dir: str) -> int:
        """Rebuild top-K state from an experiment directory after a
        driver restart: register every committed ``checkpoint_<seq>``
        child in sequence order (scores come from the metrics the gang
        commit recorded in each COMMIT marker) and skip torn ones — a
        directory truncated mid-write must never become the resume
        anchor. Returns the number of checkpoints recovered."""
        from ray_tpu.util import telemetry

        if not os.path.isdir(exp_dir):
            return 0
        found: List[Tuple[int, str]] = []
        for name in os.listdir(exp_dir):
            m = _CKPT_DIR_RE.match(name)
            if m and os.path.isdir(os.path.join(exp_dir, name)):
                found.append((int(m.group(1)), name))
        recovered = 0
        for _, name in sorted(found):
            ckpt = Checkpoint(os.path.join(exp_dir, name))
            torn = ckpt.validate_committed()
            if torn is not None:
                logger.warning(
                    "skipping torn checkpoint %s during recovery: %s",
                    ckpt.path, torn)
                telemetry.inc("ray_tpu_train_torn_checkpoint_skips_total")
                telemetry.event("train", f"torn checkpoint skipped {name}",
                                args={"reason": torn})
                continue
            info = ckpt.commit_info() or {}
            self.register(ckpt, info.get("metrics"))
            recovered += 1
        return recovered

    @staticmethod
    def next_seq_on_disk(exp_dir: str) -> int:
        """First unused ``checkpoint_<seq>`` number in ``exp_dir`` —
        restarted drivers must not clobber surviving directories."""
        seqs = [int(m.group(1)) for name in (
                    os.listdir(exp_dir) if os.path.isdir(exp_dir) else [])
                for m in [_CKPT_DIR_RE.match(name)] if m]
        return max(seqs) + 1 if seqs else 0
