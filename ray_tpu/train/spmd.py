"""GSPMD training-step construction.

Builds a sharded `init` and `train_step` for a flax model over a named
mesh: parameter shardings come from the model's logical-axis annotations
(nn.with_logical_partitioning) mapped through the rules table
(ray_tpu/parallel/sharding.py LOGICAL_RULES); optimizer state inherits the
parameter shardings; batches shard over (data, fsdp) and optionally
sequence. Everything runs under one jit — XLA inserts the collectives
(psum for gradient reduction across data axes, all-gathers for fsdp) over
ICI.

This is the TPU-native replacement for the reference's per-framework
backends (reference: train/torch/config.py NCCL process groups +
train_loop_utils.py DDP/FSDP wraps): strategy = mesh shape + rules, not a
wrapper class.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import LOGICAL_RULES, Rules


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def _rules_list(rules: Rules):
    return list(rules.items())


def make_sharded_train(
    model: nn.Module,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    example_batch: Any,
    loss_fn: Callable[[Any, Any], jax.Array],
    rules: Optional[Rules] = None,
    batch_spec: Optional[P] = None,
    donate_state: bool = True,
) -> Tuple[Callable, Callable, Any]:
    """Returns (jit_init, jit_train_step, state_shardings).

    - ``jit_init(rng)`` → TrainState, already sharded (params never
      materialize unsharded).
    - ``jit_train_step(state, batch)`` → (state, metrics dict).
    - ``loss_fn(logits_or_output, batch)`` → scalar loss; the model is
      applied to ``batch["inputs"]``.
    """
    rules = dict(rules or LOGICAL_RULES)
    # Drop rule targets the mesh doesn't have.
    for k, v in list(rules.items()):
        if isinstance(v, tuple):
            kept = tuple(a for a in v if a in mesh.axis_names)
            rules[k] = kept if kept else None
        elif isinstance(v, str) and v not in mesh.axis_names:
            rules[k] = None

    if batch_spec is None:
        from ray_tpu.parallel.mesh import data_axes

        batch_spec = P(data_axes(mesh))
    batch_sharding = jax.tree.map(
        lambda _: NamedSharding(mesh, batch_spec), example_batch
    )

    example_inputs = (
        example_batch["inputs"]
        if isinstance(example_batch, dict) else example_batch
    )

    def init_fn(rng):
        variables = model.init(rng, example_inputs)
        params = variables["params"]
        unboxed = nn.meta.unbox(params)
        opt_state = optimizer.init(unboxed)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=unboxed,
            opt_state=opt_state,
        )

    # Abstract init to derive shardings from the logical annotations.
    abs_vars = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                              example_inputs)
    logical_specs = nn.get_partition_spec(abs_vars)["params"]
    params_shardings = nn.logical_to_mesh_sharding(
        logical_specs, mesh, _rules_list(rules)
    )

    replicated = NamedSharding(mesh, P())

    abs_params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        nn.meta.unbox(abs_vars["params"]),
    )
    abs_opt = jax.eval_shape(optimizer.init, abs_params)

    def opt_sharding(subtree):
        # Param-shaped subtrees (mu/nu of adam etc.) inherit the param
        # shardings; everything else (counts, scalars) is replicated.
        if jax.tree_util.tree_structure(subtree) == jax.tree_util.\
                tree_structure(abs_params):
            return params_shardings
        return jax.tree.map(lambda _: replicated, subtree)

    is_params_like = (
        lambda x: jax.tree_util.tree_structure(x)
        == jax.tree_util.tree_structure(abs_params)
    )
    opt_shardings = jax.tree.map(
        opt_sharding, abs_opt,
        is_leaf=lambda x: x is not abs_opt and (
            is_params_like(x) or not isinstance(x, tuple)
        ),
    )
    state_shardings = TrainState(
        step=replicated, params=params_shardings, opt_state=opt_shardings
    )

    jit_init = jax.jit(init_fn, out_shardings=state_shardings)

    def train_step(state: TrainState, batch):
        def compute_loss(params):
            inputs = (batch["inputs"] if isinstance(batch, dict) else batch)
            out = model.apply({"params": params}, inputs)
            return loss_fn(out, batch)

        loss, grads = jax.value_and_grad(compute_loss)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step,
        }
        return (
            TrainState(step=state.step + 1, params=new_params,
                       opt_state=new_opt),
            metrics,
        )

    jit_train_step = jax.jit(
        train_step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate_state else (),
    )
    return jit_init, jit_train_step, state_shardings


def make_causal_lm_batch_loss():
    """Loss closure for next-token prediction: batch = {"inputs": tokens}."""
    from ray_tpu.models.llama import cross_entropy_loss

    def loss_fn(logits, batch):
        tokens = batch["inputs"] if isinstance(batch, dict) else batch
        return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])

    return loss_fn
