"""BackendExecutor: drives the worker gang through one training run.

Reference surface: python/ray/train/_internal/backend_executor.py
(start:124, start_training:438, get_next_results:552). Streams per-report
results from all ranks; rank-0's checkpoints feed the CheckpointManager.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


class TrainingWorkerError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, scaling_config: ScalingConfig,
                 backend: Optional[Backend] = None,
                 experiment_name: str = "train",
                 trial_id: str = ""):
        self.scaling = scaling_config
        self.backend = backend or JaxBackend()
        self.experiment_name = experiment_name
        self.trial_id = trial_id
        self.worker_group: Optional[WorkerGroup] = None
        self._stop_requested = False

    def start(self) -> None:
        self._stop_requested = False
        self.worker_group = WorkerGroup(
            self.scaling.total_workers,
            self.scaling.worker_resources(),
            self.scaling.placement_strategy,
        )
        world = self.worker_group.num_workers
        # Rank/topology env before any jax import in the workers
        # (reference: backend_executor._setup_gpu/TPU env propagation).
        def _env(rank: int) -> Dict[str, str]:
            env = {
                "RAY_TPU_WORLD_SIZE": str(world),
                "RAY_TPU_WORLD_RANK": str(rank),
            }
            if self.scaling.topology:
                env["RAY_TPU_TOPOLOGY"] = self.scaling.topology
            return env

        refs = [w.setup_env.remote(_env(rank))
                for rank, w in enumerate(self.worker_group.workers)]
        import ray_tpu

        ray_tpu.get(refs)
        self.backend.on_start(self.worker_group, self.scaling)

    def start_training(self, train_fn: Callable[[dict], None],
                       config: Dict[str, Any],
                       resume_checkpoint: Optional[Checkpoint] = None,
                       datasets: Optional[Dict[str, Any]] = None) -> None:
        wg = self.worker_group
        world = wg.num_workers
        refs = []
        for rank, w in enumerate(wg.workers):
            shard = None
            if datasets:
                shard = {name: _shard_for(ds, rank, world)
                         for name, ds in datasets.items()}
            refs.append(w.init_session.remote(
                dict(world_size=world, world_rank=rank, local_rank=0,
                     node_rank=rank, experiment_name=self.experiment_name,
                     trial_id=self.trial_id),
                resume_checkpoint.path if resume_checkpoint else None,
                shard))
        import ray_tpu

        ray_tpu.get(refs)
        wg.execute("start_training", train_fn, config)

    def get_next_results(self, timeout: float = 600.0
                         ) -> Optional[List[dict]]:
        """One event per rank, synchronized (reference: all ranks must
        report in lockstep). Returns None when training is done; raises on
        any rank error."""
        wg = self.worker_group
        events = wg.execute("next_report", timeout)
        kinds = {k for k, _, _ in events}
        if "error" in kinds:
            msgs = [p for k, p, _ in events if k == "error"]
            raise TrainingWorkerError("\n---\n".join(msgs))
        if "timeout" in kinds:
            raise TrainingWorkerError(
                f"worker report timed out after {timeout}s "
                "(ranks must call train.report in lockstep)")
        if kinds == {"done"}:
            return None
        if "done" in kinds:
            if self._stop_requested:
                # A cooperative stop lands on each rank at its next report,
                # so ranks legitimately finish a report or two apart. Drain
                # the stragglers to 'done' instead of calling it a desync.
                for i, (kind, _, _) in enumerate(events):
                    while kind != "done":
                        kind, payload, _ = wg.execute_single(
                            i, "next_report", timeout)
                        if kind == "error":
                            raise TrainingWorkerError(payload)
                        if kind == "timeout":
                            raise TrainingWorkerError(
                                f"worker {i} did not finish after stop "
                                f"request within {timeout}s")
                return None
            raise TrainingWorkerError(
                "ranks desynchronized: some finished while others reported")
        return [
            {"metrics": metrics, "checkpoint_path": ckpt_path, "rank": i}
            for i, (_, metrics, ckpt_path) in enumerate(events)
        ]

    def request_stop(self):
        self._stop_requested = True
        if self.worker_group is not None:
            self.worker_group.execute("request_stop")

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group)
            finally:
                self.worker_group.shutdown()
                self.worker_group = None


def _shard_for(ds, rank: int, world: int):
    """Split a dataset-like across ranks. ray_tpu.data Datasets split
    natively; lists/arrays stride; everything else is replicated."""
    split = getattr(ds, "split_for_worker", None)
    if callable(split):
        return split(rank, world)
    if isinstance(ds, (list, tuple)):
        return type(ds)(ds[rank::world])
    return ds
