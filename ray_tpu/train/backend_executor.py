"""BackendExecutor: drives the worker gang through one training run.

Reference surface: python/ray/train/_internal/backend_executor.py
(start:124, start_training:438, get_next_results:552). Streams per-report
results from all ranks; rank-0's checkpoints feed the CheckpointManager.

Gang health monitoring (reference FailureConfig semantics, TPU flavor):
a monitor thread polls every rank's ``heartbeat`` — served on the
actor's RPC lane while the train loop runs on its own thread —
independently of the report cadence. It attributes failures ("rank 3
hung in step 41" vs "rank 3 actor died"), destroys the gang's
collective groups so peers blocked in ``exchange`` wake immediately,
and pushes abort events into every live rank's outbox so a driver
blocked in ``next_report`` aborts in seconds instead of burning the
report timeout.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)

#: Consecutive heartbeat misses (timeouts / transport errors, not actor
#: death) before a rank is declared unresponsive.
_HEARTBEAT_MISS_THRESHOLD = 3


class TrainingWorkerError(RuntimeError):
    pass


class _GangHealthMonitor(threading.Thread):
    """Polls per-rank liveness + progress; aborts the gang on failure."""

    def __init__(self, executor: "BackendExecutor",
                 interval_s: float, hang_timeout_s: Optional[float]):
        super().__init__(daemon=True, name="train_gang_monitor")
        self.executor = executor
        self.interval_s = interval_s
        self.hang_timeout_s = hang_timeout_s
        self._stop = threading.Event()
        self._misses: Dict[int, int] = {}
        #: Collective group names observed in heartbeats — the destroy
        #: set on abort (queried while ranks are alive, because a dead
        #: rank can no longer be asked).
        self.seen_groups: set = set()
        #: rank -> (step, phase) last published to the timeline; one
        #: train/step:r<rank> lane marker per CHANGE, not per sweep.
        self._published: Dict[int, tuple] = {}

    def stop(self) -> None:
        self._stop.set()
        self._reset_heartbeat_gauges()

    def _reset_heartbeat_gauges(self) -> None:
        """Zero the per-rank staleness gauges this monitor published.
        Once the sweep stops, nothing updates them — without the reset
        a hung rank's last (huge) age would sit in the merged gauges
        forever, and the health plane's train_rank_stalled alert could
        never resolve after the abort."""
        from ray_tpu.util import telemetry

        for rank in self._published:
            telemetry.set_gauge(
                "ray_tpu_train_step_heartbeat_age_seconds",
                0.0, {"rank": str(rank)})

    def run(self) -> None:
        import ray_tpu
        from ray_tpu import exceptions as exc

        wg = self.executor.worker_group
        hb_timeout = max(2.0, 2 * self.interval_s)
        while not self._stop.wait(self.interval_s):
            if wg is not self.executor.worker_group:
                return  # executor moved on (shutdown/restart race)
            # Fan out all heartbeats first, then gather against ONE
            # sweep deadline: detection latency stays O(1) in world
            # size instead of one slow rank serializing the sweep.
            refs = [w.heartbeat.remote() for w in wg.workers]
            deadline = time.monotonic() + hb_timeout
            for rank, ref in enumerate(refs):
                if self._stop.is_set():
                    return
                try:
                    hb = ray_tpu.get(
                        ref, timeout=max(0.05,
                                         deadline - time.monotonic()))
                except exc.ActorDiedError as e:
                    self._abort(
                        "died", rank,
                        f"rank {rank} actor died: {e.reason or e}")
                    return
                except Exception as e:  # noqa: BLE001 — transport noise
                    misses = self._misses.get(rank, 0) + 1
                    self._misses[rank] = misses
                    logger.debug("heartbeat miss %d for rank %d: %s",
                                 misses, rank, e)
                    from ray_tpu.util import flight_recorder, telemetry

                    telemetry.event(
                        "train", "heartbeat miss",
                        args={"rank": rank, "misses": misses,
                              "error": type(e).__name__})
                    flight_recorder.record(
                        "train", "heartbeat_miss", severity="warn",
                        rank=rank, misses=misses,
                        error=type(e).__name__)
                    if misses >= _HEARTBEAT_MISS_THRESHOLD:
                        self._abort(
                            "unresponsive", rank,
                            f"rank {rank} unresponsive after {misses} "
                            f"missed heartbeats ({type(e).__name__}: {e})")
                        return
                    continue
                self._misses[rank] = 0
                self.seen_groups.update(hb.get("groups") or ())
                self._publish_step_heartbeat(rank, hb)
                if (hb.get("running") and self.hang_timeout_s
                        and hb.get("idle_s", 0.0) > self.hang_timeout_s):
                    from ray_tpu.util import flight_recorder

                    flight_recorder.record(
                        "train", "step_heartbeat_stale",
                        severity="error", rank=rank,
                        step=hb.get("reports", 0),
                        phase=hb.get("phase") or "",
                        idle_s=round(hb["idle_s"], 1))
                    self._abort(
                        "hung", rank,
                        f"{self._attribute_stall(rank, hb)} "
                        f"(no progress for {hb['idle_s']:.1f}s, "
                        f"hang_timeout_s={self.hang_timeout_s:.1f})")
                    return

    def _publish_step_heartbeat(self, rank: int, hb: Dict) -> None:
        """Per-rank observability of the device step counter: the
        staleness gauge every sweep, and a train/step:r<rank> timeline
        marker whenever the (step, phase) pair advances."""
        from ray_tpu.util import telemetry

        telemetry.set_gauge(
            "ray_tpu_train_step_heartbeat_age_seconds",
            hb.get("idle_s", 0.0), {"rank": str(rank)})
        step = hb.get("reports", 0)
        phase = hb.get("phase") or ""
        if self._published.get(rank) != (step, phase):
            self._published[rank] = (step, phase)
            telemetry.event(
                f"train/step:r{rank}",
                f"step {step} {phase or 'python'}",
                args={"rank": rank, "step": step, "phase": phase})

    @staticmethod
    def _attribute_stall(rank: int, hb: Dict) -> str:
        """Turn a stale heartbeat into a causal stall attribution using
        the step phase the rank published host-side around its jitted
        step (arXiv:2204.06514's separation: compile stall vs
        collective stall vs input/python starvation)."""
        step = hb.get("reports", 0)
        phase = hb.get("phase") or ""
        age = hb.get("phase_age_s", hb.get("idle_s", 0.0))
        if phase == "compile":
            return (f"rank {rank} hung compiling step {step} "
                    f"(in the compile phase for {age:.1f}s — XLA "
                    "compilation stall)")
        if phase == "step":
            return (f"rank {rank} hung: stalled in jitted step {step} "
                    f"(in-step for {age:.1f}s — device or collective "
                    "stall, not host python)")
        if phase:
            return (f"rank {rank} hung in {phase} phase of step {step} "
                    f"(for {age:.1f}s)")
        return (f"rank {rank} hung at python level in step {step} "
                "(no device step phase active — host-side block, e.g. "
                "input pipeline or a lock)")

    def _abort(self, kind: str, rank: int, message: str) -> None:
        if self._stop.is_set():
            return  # shutdown race: workers are being torn down on purpose
        logger.warning("gang health monitor aborting: %s", message)
        self._reset_heartbeat_gauges()
        self.executor._on_gang_failure(kind, message,
                                       groups=self.seen_groups,
                                       dead_rank=rank if kind == "died"
                                       else None)


class BackendExecutor:
    def __init__(self, scaling_config: ScalingConfig,
                 backend: Optional[Backend] = None,
                 experiment_name: str = "train",
                 trial_id: str = "",
                 failure_config: Optional[FailureConfig] = None,
                 placement_timeout_s: Optional[float] = None):
        self.scaling = scaling_config
        self.backend = backend or JaxBackend()
        self.experiment_name = experiment_name
        self.trial_id = trial_id
        self.failure_config = failure_config or FailureConfig()
        self.placement_timeout_s = (
            placement_timeout_s
            if placement_timeout_s is not None
            else self.failure_config.resource_wait_timeout_s)
        self.worker_group: Optional[WorkerGroup] = None
        self._stop_requested = False
        self._monitor: Optional[_GangHealthMonitor] = None
        self._failure_lock = threading.Lock()
        #: (kind, message) recorded by the health monitor / abort path.
        self.health_failure: Optional[Tuple[str, str]] = None

    def start(self) -> None:
        self._stop_requested = False
        self.health_failure = None
        self.worker_group = WorkerGroup(
            self.scaling.total_workers,
            self.scaling.worker_resources(),
            self.scaling.placement_strategy,
            placement_timeout_s=self.placement_timeout_s,
        )
        world = self.worker_group.num_workers
        # Rank/topology env before any jax import in the workers
        # (reference: backend_executor._setup_gpu/TPU env propagation).
        def _env(rank: int) -> Dict[str, str]:
            env = {
                "RAY_TPU_WORLD_SIZE": str(world),
                "RAY_TPU_WORLD_RANK": str(rank),
            }
            if self.scaling.topology:
                env["RAY_TPU_TOPOLOGY"] = self.scaling.topology
            return env

        refs = [w.setup_env.remote(_env(rank))
                for rank, w in enumerate(self.worker_group.workers)]
        import ray_tpu
        from ray_tpu import exceptions as exc
        from ray_tpu.train.worker_group import GangPlacementError

        try:
            # Bounded: placement budget + startup grace. Without this
            # the no-placement-group path (world=1) would block forever
            # on an unschedulable actor instead of raising into the
            # elastic-restart policy like the PG path does.
            ray_tpu.get(refs, timeout=self.placement_timeout_s + 30.0)
        except exc.GetTimeoutError as e:
            raise GangPlacementError(
                f"gang workers not schedulable within "
                f"{self.placement_timeout_s + 30.0:.1f}s "
                f"({world} x {self.scaling.worker_resources()})") from e
        self.backend.on_start(self.worker_group, self.scaling)

    def start_training(self, train_fn: Callable[[dict], None],
                       config: Dict[str, Any],
                       resume_checkpoint: Optional[Checkpoint] = None,
                       datasets: Optional[Dict[str, Any]] = None) -> None:
        wg = self.worker_group
        world = wg.num_workers
        refs = []
        for rank, w in enumerate(wg.workers):
            shard = None
            if datasets:
                shard = {name: _shard_for(ds, rank, world)
                         for name, ds in datasets.items()}
            refs.append(w.init_session.remote(
                dict(world_size=world, world_rank=rank, local_rank=0,
                     node_rank=rank, experiment_name=self.experiment_name,
                     trial_id=self.trial_id),
                resume_checkpoint.path if resume_checkpoint else None,
                shard))
        import ray_tpu

        ray_tpu.get(refs)
        wg.execute("start_training", train_fn, config)
        interval = self.failure_config.health_check_interval_s
        if interval and interval > 0:
            self._monitor = _GangHealthMonitor(
                self, interval, self.failure_config.hang_timeout_s)
            self._monitor.start()

    # -- gang failure handling ------------------------------------------

    def _on_gang_failure(self, kind: str, message: str,
                         groups: Optional[set] = None,
                         dead_rank: Optional[int] = None) -> None:
        """Record + propagate a gang failure: destroy the gang's
        collective groups (wakes ranks blocked in ``exchange``) and push
        abort events into every live rank's outbox (wakes the driver
        blocked in ``next_report``). Idempotent; first recorder wins —
        whichever of the monitor / blocked driver noticed first."""
        from ray_tpu.util import telemetry

        with self._failure_lock:
            if self.health_failure is not None:
                return
            self.health_failure = (kind, message)
        if kind == "hung":
            telemetry.inc("ray_tpu_train_hang_detections_total")
        elif kind == "died":
            telemetry.inc("ray_tpu_train_worker_deaths_total")
        telemetry.event("train", f"gang abort: {kind}",
                        args={"message": message})
        from ray_tpu.util import flight_recorder

        flight_recorder.record(
            "train", "gang_abort", severity="error", kind=kind,
            rank=dead_rank if dead_rank is not None else -1,
            message=message)
        self._destroy_collective_groups(groups or set())
        wg = self.worker_group
        if wg is None:
            return
        for rank, worker in enumerate(wg.workers):
            if rank == dead_rank:
                continue
            try:
                worker.abort_report.remote(f"gang aborted: {message}")
            except Exception:  # noqa: BLE001 — best-effort wakeup
                pass

    def _destroy_collective_groups(self, groups: set) -> None:
        if not groups:
            return
        from ray_tpu.collective import destroy_collective_group

        for name in sorted(groups):
            try:
                destroy_collective_group(name)
                logger.info("destroyed collective group %r on gang abort",
                            name)
            except Exception as e:  # noqa: BLE001 — best-effort wakeup
                logger.debug("destroy of collective group %r failed: %s",
                             name, e)

    def _rank_of_actor(self, actor_id_hex: str) -> Optional[int]:
        if not self.worker_group:
            return None
        for rank, w in enumerate(self.worker_group.workers):
            if w._actor_id.hex() == actor_id_hex:
                return rank
        return None

    def get_next_results(self, timeout: float = 600.0
                         ) -> Optional[List[dict]]:
        """One event per rank, synchronized (reference: all ranks must
        report in lockstep). Returns None when training is done; raises on
        any rank error."""
        from ray_tpu import exceptions as exc

        wg = self.worker_group
        try:
            events = wg.execute("next_report", timeout)
        except exc.ActorDiedError as e:
            # The monitor usually notices first, but the blocked driver
            # can beat its next poll tick: attribute + abort here too so
            # peers wake regardless of which side won the race.
            rank = self._rank_of_actor(e.actor_id_hex)
            msg = (f"rank {rank} actor died: {e.reason or e}"
                   if rank is not None else f"train worker died: {e}")
            monitor = self._monitor
            self._on_gang_failure(
                "died", msg,
                groups=monitor.seen_groups if monitor else set(),
                dead_rank=rank)
            raise TrainingWorkerError(self.health_failure[1]) from e
        except Exception as e:
            if self.health_failure is not None:
                raise TrainingWorkerError(self.health_failure[1]) from e
            raise
        kinds = {k for k, _, _ in events}
        if "error" in kinds:
            msgs = [p for k, p, _ in events if k == "error"]
            raise TrainingWorkerError("\n---\n".join(dict.fromkeys(msgs)))
        if "timeout" in kinds:
            raise TrainingWorkerError(
                f"worker report timed out after {timeout}s "
                "(ranks must call train.report in lockstep)")
        if kinds == {"done"}:
            return None
        if "done" in kinds:
            if self._stop_requested:
                # A cooperative stop lands on each rank at its next report,
                # so ranks legitimately finish a report or two apart. Drain
                # the stragglers to 'done' instead of calling it a desync.
                for i, (kind, _, _) in enumerate(events):
                    while kind != "done":
                        kind, payload, _ = wg.execute_single(
                            i, "next_report", timeout)
                        if kind == "error":
                            raise TrainingWorkerError(payload)
                        if kind == "timeout":
                            raise TrainingWorkerError(
                                f"worker {i} did not finish after stop "
                                f"request within {timeout}s")
                return None
            raise TrainingWorkerError(
                "ranks desynchronized: some finished while others reported")
        return [
            {"metrics": metrics, "checkpoint_path": ckpt_path, "rank": i}
            for i, (_, metrics, ckpt_path) in enumerate(events)
        ]

    def request_stop(self):
        self._stop_requested = True
        if self.worker_group is not None:
            self.worker_group.execute("request_stop")

    def shutdown(self):
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group)
            finally:
                self.worker_group.shutdown()
                self.worker_group = None


def _shard_for(ds, rank: int, world: int):
    """Split a dataset-like across ranks. ray_tpu.data Datasets split
    natively; lists/arrays stride; everything else is replicated."""
    split = getattr(ds, "split_for_worker", None)
    if callable(split):
        return split(rank, world)
    if isinstance(ds, (list, tuple)):
        return type(ds)(ds[rank::world])
    return ds
