"""Training backends: process-group/device-world setup hooks.

Reference surface: python/ray/train/backend.py (Backend ABC) +
train/torch/config.py:62-147 (_TorchBackend building NCCL process groups).
The TPU-native backend replaces NCCL bootstrap with
``jax.distributed.initialize``: after on_start, ``jax.devices()`` on every
worker spans the whole slice and GSPMD programs (ray_tpu/train/spmd.py)
sync gradients in-graph over ICI — there is no out-of-graph gradient
plane to configure (SURVEY.md §3.4 TPU mapping).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


class Backend:
    def on_start(self, worker_group: WorkerGroup,
                 scaling_config: ScalingConfig) -> None:
        """Called after workers start, before the train loop."""

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        """Called before workers are torn down."""


def _jax_distributed_init(coordinator: str, num_processes: int,
                          process_id: int) -> None:
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def _jax_distributed_shutdown() -> None:
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class JaxBackend(Backend):
    """Bootstraps the jax device world across the worker gang.

    ``distributed=None`` (auto): initialize jax.distributed only for
    multi-worker TPU gangs — each worker is one host of a slice. On
    single-host or CPU test gangs, workers keep independent device worlds
    and host-plane sync goes through ray_tpu.collective.
    """

    def __init__(self, distributed: Optional[bool] = None,
                 coordinator_port: Optional[int] = None):
        self.distributed = distributed
        self.coordinator_port = coordinator_port
        self._initialized = False

    def _should_init(self, scaling: ScalingConfig, world: int) -> bool:
        if self.distributed is not None:
            return self.distributed and world > 1
        return scaling.use_tpu and world > 1

    def on_start(self, worker_group: WorkerGroup,
                 scaling_config: ScalingConfig) -> None:
        world = worker_group.num_workers
        if not self._should_init(scaling_config, world):
            return
        ip = worker_group.execute_single(0, "node_ip")
        port = (self.coordinator_port or
                worker_group.execute_single(0, "find_free_port"))
        coordinator = f"{ip}:{port}"
        import ray_tpu

        refs = [
            w.execute.remote(_jax_distributed_init, coordinator, world, i)
            for i, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=120)
        self._initialized = True

    def on_shutdown(self, worker_group: WorkerGroup) -> None:
        if self._initialized:
            self._initialized = False
            try:
                worker_group.execute("execute", _jax_distributed_shutdown)
            except Exception:
                pass
