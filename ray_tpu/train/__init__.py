"""ray_tpu.train — distributed training orchestration (reference:
python/ray/train/) + GSPMD train-step construction (spmd.py)."""

from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import (
    CheckpointManager,
    TornCheckpointError,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.result import Result
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    instrument_step,
    report,
    step_phase,
)
from ray_tpu.train.predictor import JaxPredictor, predict_dataset
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer
from ray_tpu.train.worker_group import GangPlacementError

__all__ = [
    "Backend",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "FailureConfig",
    "GangPlacementError",
    "JaxBackend",
    "JaxPredictor",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TornCheckpointError",
    "TrainContext",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "instrument_step",
    "predict_dataset",
    "report",
    "step_phase",
]
