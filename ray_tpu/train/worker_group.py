"""WorkerGroup: the gang of training-worker actors.

Reference surface: python/ray/train/_internal/worker_group.py:102,188 —
N actors with per-worker resources, ``execute`` fan-out. TPU delta: the
group is gang-placed via a placement group (one bundle per worker,
STRICT_PACK-by-slice when a topology is set) because a pod slice is one
failure/placement domain (SURVEY.md §7.3 item 2).
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional


class GangPlacementError(RuntimeError):
    """The gang's placement group did not become placeable in time —
    distinct from worker failures so the trainer's elastic-restart
    policy can shrink the gang instead of burning a restart attempt."""


class TrainWorker:
    """Actor body: hosts the user's train loop + the report outbox."""

    def __init__(self, world_rank: int):
        self.world_rank = world_rank
        self._thread: Optional[threading.Thread] = None
        self._session = None

    def setup_env(self, env: Dict[str, str]) -> str:
        os.environ.update(env)
        # The container's sitecustomize force-sets jax_platforms to the
        # tunneled TPU in every interpreter; honor an explicit JAX_PLATFORMS
        # (tests run workers on the virtual CPU mesh this way).
        if "JAX_PLATFORMS" in os.environ:
            try:
                import jax

                jax.config.update("jax_platforms",
                                  os.environ["JAX_PLATFORMS"])
            except Exception:
                pass
        return socket.gethostname()

    def node_ip(self) -> str:
        # UDP-connect trick: picks the interface a default route would use,
        # avoiding the 127.0.0.1 that /etc/hosts often maps hostnames to
        # (no packet is sent). Reference behavior: ray get_node_ip_address.
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        except OSError:
            return socket.gethostbyname(socket.gethostname())
        finally:
            s.close()

    def find_free_port(self) -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (reference:
        worker_group.py execute)."""
        return fn(*args, **kwargs)

    def init_session(self, context_kwargs: dict,
                     resume_checkpoint_path: Optional[str],
                     datasets: Optional[dict] = None) -> None:
        from ray_tpu.train import session as session_mod
        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.train.session import TrainContext

        ckpt = (Checkpoint(resume_checkpoint_path)
                if resume_checkpoint_path else None)
        self._session = session_mod._init_session(
            TrainContext(**context_kwargs), ckpt, datasets)

    def start_training(self, train_fn: Callable, config: dict) -> None:
        """Launch the user loop on a thread; results stream via
        next_report()."""
        assert self._session is not None, "init_session first"
        sess = self._session

        def runner():
            from ray_tpu.train.session import StopTraining

            try:
                train_fn(config)
                sess.outbox.put(("done", None, None))
            except StopTraining:
                sess.outbox.put(("done", None, None))
            except BaseException as e:  # noqa: BLE001 — ships to driver
                sess.outbox.put(
                    ("error", f"{type(e).__name__}: {e}\n"
                              f"{traceback.format_exc()}", None))

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="train_loop")
        self._thread.start()

    def next_report(self, timeout: float = 600.0):
        """Block for the next (kind, metrics, checkpoint_path) event."""
        sess = self._session
        try:
            kind, payload, ckpt = sess.outbox.get(timeout=timeout)
        except queue.Empty:
            return ("timeout", None, None)
        return (kind, payload, ckpt.path if ckpt is not None else None)

    def request_stop(self) -> None:
        if self._session is not None:
            self._session.stop_requested.set()

    def heartbeat(self) -> Dict[str, Any]:
        """Liveness + progress probe for the gang health monitor. Runs
        on the actor's RPC lane (the train loop is a separate thread),
        so it answers even while the loop is wedged in a collective —
        that is exactly what lets the monitor tell 'hung' from 'dead'."""
        from ray_tpu.collective.collective import local_group_names

        sess = self._session
        out: Dict[str, Any] = {"rank": self.world_rank,
                               "ready": sess is not None}
        if sess is None:
            return out
        thread = self._thread
        out.update(
            reports=sess.report_count,
            running=bool(thread is not None and thread.is_alive()),
            idle_s=time.monotonic() - sess.last_activity,
            groups=local_group_names(),
            # Device step-counter heartbeat (session.step_phase /
            # instrument_step): which phase of the step the loop is in
            # and for how long — the monitor's hang attribution input.
            phase=sess.step_phase,
            phase_age_s=time.monotonic() - sess.phase_since,
        )
        return out

    def abort_report(self, reason: str) -> None:
        """Driver-side gang abort: push an error event into the report
        outbox so a driver blocked in next_report() wakes immediately
        instead of burning the report timeout, and ask the user loop to
        unwind at its next report."""
        if self._session is None:
            return
        self._session.stop_requested.set()
        self._session.outbox.put(("error", reason, None))

    def chaos_hang(self, duration_s: float) -> None:
        """Chaos lane: stall this rank's train loop (not its RPC lane)
        for ``duration_s`` at its next report — simulates a wedged
        device/collective that the health monitor must flag as a hang."""
        if self._session is not None:
            self._session.chaos_hang_until = (
                time.monotonic() + duration_s)

    def shutdown_session(self) -> None:
        from ray_tpu.train import session as session_mod

        session_mod._shutdown_session()
        self._session = None


class WorkerGroup:
    def __init__(self, num_workers: int, resources: Dict[str, float],
                 placement_strategy: str = "PACK",
                 placement_timeout_s: float = 60.0):
        import ray_tpu

        self.num_workers = num_workers
        self.pg = None
        actor_cls = ray_tpu.remote(TrainWorker)
        common = dict(
            num_cpus=resources.get("CPU", 0.0),
            num_tpus=resources.get("TPU", 0.0),
            memory=resources.get("memory"),
            resources={k: v for k, v in resources.items()
                       if k not in ("CPU", "TPU", "memory")} or None,
            # The health monitor's heartbeat/abort_report calls must be
            # served while next_report blocks inside the actor, so the
            # worker cannot be a one-lane sync actor.
            max_concurrency=8,
        )
        if num_workers > 1:
            from ray_tpu.core.task_spec import (
                PlacementGroupSchedulingStrategy,
            )

            self.pg = ray_tpu.placement_group(
                [dict(resources) for _ in range(num_workers)],
                strategy=placement_strategy)
            try:
                if not self.pg.ready(timeout=placement_timeout_s):
                    raise GangPlacementError(
                        "placement group for worker gang not placeable "
                        f"within {placement_timeout_s:.1f}s "
                        f"({num_workers} x {resources})")
                self.workers = [
                    actor_cls.options(
                        scheduling_strategy=PlacementGroupSchedulingStrategy(
                            placement_group_id_hex=self.pg.id_hex,
                            bundle_index=i),
                        **common).remote(i)
                    for i in range(num_workers)
                ]
            except BaseException:
                ray_tpu.remove_placement_group(self.pg)
                raise
        else:
            self.workers = [actor_cls.options(**common).remote(0)]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call a TrainWorker method on every worker, gather results."""
        import ray_tpu

        refs = [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]
        return ray_tpu.get(refs)

    def execute_single(self, rank: int, method: str, *args, **kwargs):
        import ray_tpu

        return ray_tpu.get(
            getattr(self.workers[rank], method).remote(*args, **kwargs))

    def execute_async(self, method: str, *args, **kwargs):
        return [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]

    def shutdown(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                ray_tpu.remove_placement_group(self.pg)
            except Exception:
                pass
        self.workers = []
