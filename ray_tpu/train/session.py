"""Per-worker training session: report/context/checkpoint access.

Reference surface: python/ray/train/_internal/session.py (report:653,
get_context, get_checkpoint). The session is process-global inside a
training worker; ``report`` hands (metrics, checkpoint) to the worker's
outbox, which the driver-side BackendExecutor streams via next_report().
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import queue
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.util import device_trace

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


@dataclasses.dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    experiment_name: str
    trial_id: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _TrainSession:
    def __init__(self, context: TrainContext,
                 resume_checkpoint: Optional[Checkpoint],
                 datasets: Optional[Dict[str, Any]] = None):
        self.context = context
        self.resume_checkpoint = resume_checkpoint
        self.datasets = datasets or {}
        self.outbox: "queue.Queue" = queue.Queue()
        self.stop_requested = threading.Event()
        self._last_report_t = time.perf_counter()
        # Gang-health bookkeeping, read by TrainWorker.heartbeat():
        # report_count is the monitor's notion of per-rank progress,
        # last_activity its staleness clock (monotonic).
        self.report_count = 0
        self.last_activity = time.monotonic()
        # Device step-counter heartbeat (live profiling plane): the
        # train loop advances step_phase host-side around its jitted
        # step (step_phase()/instrument_step below), so the gang
        # monitor can attribute a stall to "compiling" vs "stuck in
        # the jitted step (device/collective)" vs "blocked at python
        # level" instead of a generic hang. "" = python-level code
        # between phases.
        self.step_phase = ""
        self.phase_since = time.monotonic()
        # Chaos lane (util/chaos.py TrainWorkerKiller "hang" mode):
        # stalls the train loop inside report() WITHOUT blocking the
        # actor's RPC loop, so heartbeats stay healthy while progress
        # stops — exactly the signature of a wedged collective/device.
        self.chaos_hang_until = 0.0

    def set_phase(self, phase: str) -> None:
        self.step_phase = phase
        self.phase_since = time.monotonic()
        # Mirror every phase edge into the device-trace recorder's
        # wall-clock window ring, so a jax.profiler capture of this
        # process can attribute each XLA op span to "step N /
        # compile|execute" for this rank.
        device_trace.note_phase(phase, rank=self.context.world_rank)

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        from ray_tpu.util import telemetry

        # Save/restore like step_phase(): report() may run INSIDE an
        # enclosing phase context, and clobbering it to "" would
        # misattribute a later stall in that context to python level.
        prev_phase = self.step_phase
        self.set_phase("report")
        while (time.monotonic() < self.chaos_hang_until
               and not self.stop_requested.is_set()):
            time.sleep(0.05)
        now = time.perf_counter()
        # report() is called once per step by convention, so the gap
        # between consecutive calls IS the step time.
        telemetry.observe("ray_tpu_train_step_seconds",
                          now - self._last_report_t)
        telemetry.inc("ray_tpu_train_reports_total")
        self._last_report_t = now
        self.report_count += 1
        self.last_activity = time.monotonic()
        self.outbox.put(("report", dict(metrics), checkpoint))
        self.set_phase(prev_phase)
        # Cooperative early stop (Tune schedulers): raising here unwinds
        # the user loop; the executor turns it into a clean finish.
        if self.stop_requested.is_set():
            raise StopTraining()


class StopTraining(Exception):
    """Raised inside the user train loop on scheduler-requested stop."""


def _init_session(context: TrainContext,
                  resume_checkpoint: Optional[Checkpoint],
                  datasets: Optional[Dict[str, Any]] = None
                  ) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(context, resume_checkpoint, datasets)
        return _session


def _shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


def _get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active — train.report()/get_context() are "
            "only valid inside a train_loop_per_worker")
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint) from the train loop
    (reference: train/_internal/session.py:653)."""
    _get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return _get_session().context


def get_checkpoint() -> Optional[Checkpoint]:
    """Latest checkpoint to resume from (set on restart after failure)."""
    return _get_session().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer
    (reference: session.get_dataset_shard)."""
    ds = _get_session().datasets.get(name)
    if ds is None:
        raise KeyError(f"no dataset named {name!r} was passed to the trainer")
    return ds


@contextlib.contextmanager
def step_phase(phase: str):
    """Mark the train loop as inside ``phase`` — the device
    step-counter heartbeat the gang health monitor reads. Use
    ``"compile"`` around explicit AOT compilation and ``"step"`` around
    the jitted step call (or wrap the step with ``instrument_step``,
    which does both); a rank that wedges inside the context is then
    attributed to that phase instead of a generic hang."""
    sess = _get_session()
    prev = sess.step_phase
    sess.set_phase(phase)
    try:
        yield
    finally:
        sess.set_phase(prev)


def instrument_step(step_fn):
    """Wrap a (jitted) train-step callable for the device step-counter
    heartbeat: the first call — where jit traces and XLA compiles — is
    attributed to the ``compile`` phase, every later call to ``step``.
    Advanced host-side around the call, so a wedged collective inside
    the step shows up as stalled-in-step within the hang timeout."""
    state = {"compiled": False}

    @functools.wraps(step_fn)
    def wrapped(*args, **kwargs):
        phase = "step" if state["compiled"] else "compile"
        with step_phase(phase):
            out = step_fn(*args, **kwargs)
        state["compiled"] = True
        return out

    return wrapped
