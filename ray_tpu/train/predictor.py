"""JaxPredictor: batch inference from a checkpoint.

Reference: python/ray/train/torch/torch_predictor.py + the
Dataset.map_batches(ActorPoolStrategy) batch-inference pattern. The
TPU-first shape: the predictor jit-compiles one forward, keeps it warm
across batches, and `predict_dataset` runs predictors as stateful
dataset actors so each replica pins its device and compiles once.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np


class JaxPredictor:
    """Wraps (apply_fn, params): jit once, predict numpy batches."""

    def __init__(self, apply_fn: Callable, params: Any,
                 output_column: str = "predictions"):
        import jax

        self._fn = jax.jit(apply_fn)
        self._params = params
        self._output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint, apply_fn: Callable,
                        **kwargs) -> "JaxPredictor":
        """checkpoint: ray_tpu.train.Checkpoint written by from_pytree.
        Multi-shard (per-rank) checkpoints are rejected — silently using
        one rank's partial parameters would produce wrong predictions."""
        shards = checkpoint.shard_files()
        if len(shards) > 1:
            raise ValueError(
                f"checkpoint {checkpoint.path} has {len(shards)} "
                "per-rank shards; consolidate to a single replicated "
                "shard before inference")
        params = checkpoint.to_pytree(shard_rank=0)
        return cls(apply_fn, params, **kwargs)

    def predict(self, batch) -> Dict[str, np.ndarray]:
        """batch: ndarray or dict of ndarrays -> {output_column: preds}."""
        import jax.numpy as jnp

        data = (next(iter(batch.values()))
                if isinstance(batch, dict) and len(batch) == 1 else batch)
        if isinstance(data, dict):
            arg = {k: jnp.asarray(v) for k, v in data.items()}
        else:
            arg = jnp.asarray(data)
        out = self._fn(self._params, arg)
        return {self._output_column: np.asarray(out)}


def predict_dataset(dataset, *, checkpoint, apply_fn: Callable,
                    batch_size: int = 256, concurrency: int = 1,
                    num_tpus_per_replica: float = 0.0,
                    output_column: str = "predictions"):
    """Distributed batch inference: predictor replicas as stateful
    dataset actors (each compiles once, streams batches through the
    cached executable).

    ``apply_fn`` must be row-independent: ragged trailing batches are
    zero-padded to ``batch_size`` to avoid jit retraces, so a function
    that mixes information across the batch axis (train-mode batchnorm,
    batch-axis softmax) would see the padding rows.
    """
    if num_tpus_per_replica:
        from ray_tpu.core.accelerators import TPUAcceleratorManager

        # Fail at the API boundary, not deep inside actor creation.
        TPUAcceleratorManager.validate_chip_request(num_tpus_per_replica)

    class _PredictorUDF:
        def __init__(self, ckpt, output_col, bs):
            self.predictor = JaxPredictor.from_checkpoint(
                ckpt, apply_fn, output_column=output_col)
            self.bs = bs

        def __call__(self, batch):
            # Pad ragged trailing batches to the full batch size so the
            # jit executable compiles once (a new shape would retrace);
            # slice the outputs back. predict() handles the single-column
            # dict unwrap.
            data = batch
            if isinstance(data, dict) and len(data) == 1:
                data = next(iter(data.values()))
            n = (len(next(iter(data.values())))
                 if isinstance(data, dict) else len(data))
            if n < self.bs:
                def pad(a):
                    widths = [(0, self.bs - n)] + [(0, 0)] * (a.ndim - 1)
                    return np.pad(a, widths)

                data = ({k: pad(v) for k, v in data.items()}
                        if isinstance(data, dict) else pad(data))
            out = self.predictor.predict(data)
            if n < self.bs:
                out = {k: v[:n] for k, v in out.items()}
            return out

    kwargs: Dict[str, Any] = {}
    if num_tpus_per_replica:
        kwargs["num_tpus"] = num_tpus_per_replica
    return dataset.map_batches(
        _PredictorUDF,
        fn_constructor_args=(checkpoint, output_column, batch_size),
        batch_size=batch_size, concurrency=concurrency, **kwargs)
