"""JaxTrainer: the user-facing distributed trainer.

Reference surface: python/ray/train/base_trainer.py:581 (fit),
data_parallel_trainer.py:26 (training_loop shape), restore(:316).
Differences by design: the trainer drives the BackendExecutor directly —
Tune integration is an explicit wrapper (ray_tpu.tune builds a Trainable
from any trainer via ``as_trainable``) instead of every fit() routing
through a Tune controller.

Failure handling (reference FailureConfig semantics, TPU gang flavor):
any worker failure kills the whole gang; up to ``max_failures`` restarts
re-run the loop from the latest registered checkpoint via
``session.get_checkpoint()``. Restarts back off exponentially
(core/retry.RetryPolicy), wait up to ``resource_wait_timeout_s`` for the
gang's placement group, and may elastically re-form a smaller gang down
to ``min_workers`` when the dead node's resources never return —
datasets are re-sharded for the new world size.

Checkpoint commit discipline: reported per-rank checkpoint dirs merge
into a hidden staging directory; the COMMIT marker (shard set + sizes +
metrics) is rewritten there and the staging dir is atomically renamed to
``checkpoint_<seq>`` only after every shard landed. A driver crash can
leave stale staging dirs but never a torn ``checkpoint_<seq>``; on the
next fit() ``CheckpointManager.recover_from_dir`` rebuilds top-K state
from the committed directories and skips anything torn.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    TrainingWorkerError,
)
from ray_tpu.train.checkpoint import Checkpoint, _fsync_dir
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.result import Result
from ray_tpu.train.worker_group import GangPlacementError

logger = logging.getLogger(__name__)

#: Staging-dir prefix for in-flight gang commits. Dot-prefixed so
#: nothing scanning for ``checkpoint_*`` (tests, recovery, users) can
#: mistake a partially-merged directory for a real checkpoint.
_STAGING_PREFIX = ".staging_checkpoint_"

#: Placement probe budget per shrunken gang size during elastic
#: formation (the configured resource_wait_timeout_s is spent waiting
#: for the FULL gang first; smaller sizes just need a quick yes/no).
_SHRINK_PROBE_TIMEOUT_S = 5.0


def _merge_move_tree(src: str, dest: str) -> None:
    """Merge ``src`` into ``dest`` by renaming files (zero-copy on one
    filesystem — checkpoints live on shared storage); byte-copy only as a
    cross-device fallback. Checkpoint dirs can be multi-GB, so a copytree
    here would double every report's I/O."""
    for root, dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        target_dir = dest if rel == "." else os.path.join(dest, rel)
        os.makedirs(target_dir, exist_ok=True)
        for name in files:
            s = os.path.join(root, name)
            d = os.path.join(target_dir, name)
            try:
                os.replace(s, d)
            except OSError:
                shutil.copy2(s, d)
    shutil.rmtree(src, ignore_errors=True)


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable[[dict], None],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend: Optional[Backend] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend = backend or JaxBackend()
        self.datasets = datasets
        self.resume_from_checkpoint = resume_from_checkpoint

    # -- storage layout ----------------------------------------------------

    def _experiment_dir(self) -> str:
        name = self.run_config.name or f"jax_trainer_{int(time.time())}"
        path = os.path.join(self.run_config.resolved_storage_path(), name)
        os.makedirs(path, exist_ok=True)
        return path

    # -- elastic gang formation --------------------------------------------

    def _form_executor(self, world: int, failure_config: FailureConfig,
                       exp_dir: str, placement_timeout_s: float
                       ) -> BackendExecutor:
        scaling = (self.scaling_config if world ==
                   self.scaling_config.total_workers else
                   dataclasses.replace(self.scaling_config,
                                       num_workers=world))
        executor = BackendExecutor(
            scaling, self.backend,
            experiment_name=os.path.basename(exp_dir),
            failure_config=failure_config,
            placement_timeout_s=placement_timeout_s)
        try:
            executor.start()
        except BaseException:
            executor.shutdown()  # reap a half-formed gang
            raise
        return executor

    def _probe_placeable(self, world: int, timeout_s: float) -> bool:
        """Cheap placeability probe: a throwaway placement group, no
        actors. Racy by nature (resources can vanish between probe and
        formation) — formation failure afterwards still raises into the
        restart policy."""
        import ray_tpu

        resources = self.scaling_config.worker_resources()
        pg = ray_tpu.placement_group(
            [dict(resources) for _ in range(world)],
            strategy=self.scaling_config.placement_strategy)
        try:
            return bool(pg.ready(timeout=timeout_s))
        finally:
            ray_tpu.remove_placement_group(pg)

    def _form_gang(self, failure_config: FailureConfig,
                   exp_dir: str) -> BackendExecutor:
        """Start a worker gang at full size, waiting up to
        ``resource_wait_timeout_s`` for placement; when the cluster
        cannot place the full gang (e.g. a dead node's resources never
        returned), binary-search the largest placeable size down to
        ``min_workers`` (placeability is monotone in gang size, so this
        is O(log n) probes, not O(n) gang formations) and run
        elastically at that size."""
        from ray_tpu.util import telemetry

        full = self.scaling_config.total_workers
        min_workers = failure_config.min_workers or full
        min_workers = max(1, min(min_workers, full))
        try:
            return self._form_executor(
                full, failure_config, exp_dir,
                failure_config.resource_wait_timeout_s)
        except GangPlacementError as e:
            if min_workers >= full:
                raise
            last = e
        probe_timeout = min(_SHRINK_PROBE_TIMEOUT_S,
                            failure_config.resource_wait_timeout_s)
        if not self._probe_placeable(min_workers, probe_timeout):
            raise GangPlacementError(
                f"no gang size in [{min_workers}, {full}] was placeable "
                f"within the resource wait budget") from last
        lo, hi = min_workers, full - 1  # lo is known placeable
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self._probe_placeable(mid, probe_timeout):
                lo = mid
            else:
                hi = mid - 1
        executor = self._form_executor(lo, failure_config, exp_dir,
                                       probe_timeout)
        logger.warning(
            "elastic restart: re-formed gang at %d/%d workers "
            "(full-size placement unavailable); datasets re-shard "
            "for the new world size", lo, full)
        telemetry.inc("ray_tpu_train_elastic_resizes_total")
        telemetry.event("train", "elastic gang resize",
                        args={"from": full, "to": lo})
        from ray_tpu.util import flight_recorder

        flight_recorder.record("train", "elastic_resize", severity="warn",
                               from_world=full, to_world=lo)
        return executor

    # -- fit ---------------------------------------------------------------

    def fit(self) -> Result:
        from ray_tpu.core.retry import RetryPolicy
        from ray_tpu.util import telemetry

        exp_dir = self._experiment_dir()
        ckpt_config = self.run_config.checkpoint_config or CheckpointConfig()
        failure_config = self.run_config.failure_config or FailureConfig()
        manager = CheckpointManager(ckpt_config)
        # Crash recovery: committed checkpoints from a previous driver
        # run (same experiment dir) rebuild top-K state; torn dirs are
        # skipped, stale staging dirs swept. RunConfig.auto_resume=False
        # opts a deliberate from-scratch rerun out of the resume.
        self._sweep_staging(exp_dir)
        if self.run_config.auto_resume:
            recovered = manager.recover_from_dir(exp_dir)
            if recovered:
                logger.info(
                    "recovered %d committed checkpoint(s) from %s "
                    "(auto_resume=False for a fresh run)",
                    recovered, exp_dir)
        ckpt_seq = CheckpointManager.next_seq_on_disk(exp_dir)
        # An explicitly passed checkpoint out-ranks disk recovery at run
        # start (the user may be deliberately rolling back past a bad
        # latest); after an in-run failure the freshest committed
        # checkpoint is the right anchor again.
        resume = self.resume_from_checkpoint or manager.latest
        history: list = []
        last_metrics: Dict[str, Any] = {}
        attempts = failure_config.max_failures + 1
        backoff = RetryPolicy(
            max_attempts=max(attempts, 2),
            base_delay_s=failure_config.restart_backoff_s,
            max_delay_s=max(failure_config.restart_backoff_s * 8, 30.0),
            jitter=0.25)
        error: Optional[str] = None

        for attempt in range(attempts):
            if attempt > 0 and failure_config.restart_backoff_s > 0:
                delay = backoff.backoff_delay(attempt - 1)
                logger.info("backing off %.2fs before restart %d/%d",
                            delay, attempt, attempts - 1)
                time.sleep(delay)
            executor: Optional[BackendExecutor] = None
            try:
                executor = self._form_gang(failure_config, exp_dir)
                self._warn_shard_mismatch(executor, resume)
                executor.start_training(
                    self.train_loop, self.train_loop_config,
                    resume_checkpoint=resume, datasets=self.datasets)
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    rank0 = results[0]
                    last_metrics = rank0["metrics"]
                    history.append(dict(last_metrics))
                    ckpt = self._collect_checkpoint(
                        results, exp_dir, ckpt_seq, last_metrics)
                    ckpt_seq += 1
                    if ckpt is not None:
                        manager.register(ckpt, last_metrics)
                        resume = manager.latest
                error = None
                break
            except Exception as e:  # worker death, report error, infra
                error = str(e)
                reason = "error"
                if executor is not None and executor.health_failure:
                    reason = executor.health_failure[0]
                elif isinstance(e, GangPlacementError):
                    reason = "placement"
                logger.warning(
                    "training attempt %d/%d failed (%s): %s",
                    attempt + 1, attempts, reason, e)
                if attempt + 1 < attempts:
                    telemetry.inc("ray_tpu_train_restarts_total", 1,
                                  {"reason": reason})
                    telemetry.event("train", "gang restart",
                                    args={"attempt": attempt + 1,
                                          "reason": reason})
                    from ray_tpu.util import flight_recorder

                    flight_recorder.record(
                        "train", "gang_restart", severity="warn",
                        attempt=attempt + 1, reason=reason)
                resume = manager.latest or self.resume_from_checkpoint
            finally:
                if executor is not None:
                    executor.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=manager.latest,
            path=exp_dir,
            error=error,
            metrics_history=history,
            best_checkpoint=manager.best,
        )

    @staticmethod
    def _warn_shard_mismatch(executor: BackendExecutor,
                             resume: Optional[Checkpoint]) -> None:
        """An elastically shrunken gang resuming a checkpoint sharded
        for a larger world would silently drop the lost ranks' shards
        (each rank restores only its own shard): surface it loudly —
        per-rank-sharded state needs user-side re-sharding, replicated
        (single-shard) checkpoints resume cleanly at any size."""
        if resume is None or executor.worker_group is None:
            return
        try:
            shards = len(resume.shard_files())
        except OSError:
            return
        world = executor.worker_group.num_workers
        if shards > max(world, 1):
            from ray_tpu.util import telemetry

            logger.warning(
                "resume checkpoint %s has %d per-rank shards but the "
                "gang re-formed with only %d workers: shards beyond "
                "rank %d will NOT be restored by any rank. Re-shard the "
                "checkpoint (or save replicated state from rank 0) "
                "before shrinking.", resume.path, shards, world,
                world - 1)
            telemetry.event("train", "shard/world mismatch on resume",
                            args={"shards": shards, "world": world})

    # -- checkpoint collection ---------------------------------------------

    @staticmethod
    def _sweep_staging(exp_dir: str) -> None:
        """Remove staging dirs a crashed driver left behind — by
        construction they never contain the only copy of a committed
        checkpoint."""
        for name in os.listdir(exp_dir):
            if name.startswith(_STAGING_PREFIX):
                shutil.rmtree(os.path.join(exp_dir, name),
                              ignore_errors=True)

    def _collect_checkpoint(self, results, exp_dir: str, seq: int,
                            metrics: Optional[dict] = None
                            ) -> Optional[Checkpoint]:
        """Gang-commit reported checkpoint dirs into the experiment dir.
        Multi-rank reports merge into one staging directory (each rank
        wrote distinct shard files — the orbax recipe); the COMMIT
        marker is rewritten from the merged shard set (+ report
        metrics, for recover_from_dir), and only then is the directory
        atomically renamed to its final ``checkpoint_<seq>`` name. A
        crash at any point leaves either the previous state or a
        sweepable staging dir — never a torn checkpoint."""
        paths = [r["checkpoint_path"] for r in results
                 if r["checkpoint_path"]]
        if not paths:
            return None
        dest = os.path.join(exp_dir, f"checkpoint_{seq:06d}")
        staging = os.path.join(exp_dir, f"{_STAGING_PREFIX}{seq:06d}")
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging)
        for p in dict.fromkeys(paths):  # dedupe, keep order
            # A rank that reported dest itself (wrote straight into the
            # final location) merges like any other source — its files
            # move to staging and come back at the rename below, instead
            # of being destroyed with the stale dest.
            if os.path.isdir(p):
                _merge_move_tree(p, staging)
        staged = Checkpoint(staging)
        # The authoritative commit: every rank that reported has merged
        # its shards by now, so expected set == observed set, with exact
        # sizes. Metrics ride along so recover_from_dir can re-score.
        staged.commit(extra={"metrics": metrics or {}, "seq": seq})
        if os.path.exists(dest):
            # A previous driver crashed between writing dest and
            # recording it (rename is the commit point), or a rank
            # reported dest directly (its files are in staging now
            # either way). This seq belongs to the current run: replace.
            shutil.rmtree(dest, ignore_errors=True)
        os.replace(staging, dest)
        # The rename IS the commit: make it durable (the shard/marker
        # writers fsync their files and the staging dir, but the final
        # directory-entry swap lives in exp_dir's journal).
        _fsync_dir(exp_dir)
        return Checkpoint(dest)

    def as_trainable(self):
        """Adapter for ray_tpu.tune: a function trainable closing over this
        trainer's configs; Tune overrides train_loop_config per trial."""
        base = self

        def trainable(config: dict):
            merged = dict(base.train_loop_config)
            merged.update(config)
            trainer = JaxTrainer(
                base.train_loop,
                train_loop_config=merged,
                scaling_config=base.scaling_config,
                run_config=base.run_config,
                backend=base.backend,
                datasets=base.datasets,
                resume_from_checkpoint=base.resume_from_checkpoint,
            )
            result = trainer.fit()
            if result.error:
                raise RuntimeError(result.error)
            return result.metrics

        trainable.__name__ = "jax_trainer"
        return trainable


# Alias matching the reference's family naming (TorchTrainer et al.)
DataParallelTrainer = JaxTrainer
