"""JaxTrainer: the user-facing distributed trainer.

Reference surface: python/ray/train/base_trainer.py:581 (fit),
data_parallel_trainer.py:26 (training_loop shape), restore(:316).
Differences by design: the trainer drives the BackendExecutor directly —
Tune integration is an explicit wrapper (ray_tpu.tune builds a Trainable
from any trainer via ``as_trainable``) instead of every fit() routing
through a Tune controller.

Failure handling (reference FailureConfig semantics, TPU gang flavor):
any worker failure kills the whole gang; up to ``max_failures`` restarts
re-run the loop from the latest registered checkpoint via
``session.get_checkpoint()``.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    TrainingWorkerError,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.result import Result

logger = logging.getLogger(__name__)


def _merge_move_tree(src: str, dest: str) -> None:
    """Merge ``src`` into ``dest`` by renaming files (zero-copy on one
    filesystem — checkpoints live on shared storage); byte-copy only as a
    cross-device fallback. Checkpoint dirs can be multi-GB, so a copytree
    here would double every report's I/O."""
    for root, dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        target_dir = dest if rel == "." else os.path.join(dest, rel)
        os.makedirs(target_dir, exist_ok=True)
        for name in files:
            s = os.path.join(root, name)
            d = os.path.join(target_dir, name)
            try:
                os.replace(s, d)
            except OSError:
                shutil.copy2(s, d)
    shutil.rmtree(src, ignore_errors=True)


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable[[dict], None],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend: Optional[Backend] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend = backend or JaxBackend()
        self.datasets = datasets
        self.resume_from_checkpoint = resume_from_checkpoint

    # -- storage layout ----------------------------------------------------

    def _experiment_dir(self) -> str:
        name = self.run_config.name or f"jax_trainer_{int(time.time())}"
        path = os.path.join(self.run_config.resolved_storage_path(), name)
        os.makedirs(path, exist_ok=True)
        return path

    def fit(self) -> Result:
        exp_dir = self._experiment_dir()
        ckpt_config = self.run_config.checkpoint_config or CheckpointConfig()
        failure_config = self.run_config.failure_config or FailureConfig()
        manager = CheckpointManager(ckpt_config)
        resume = self.resume_from_checkpoint
        history: list = []
        last_metrics: Dict[str, Any] = {}
        attempts = failure_config.max_failures + 1
        error: Optional[str] = None

        for attempt in range(attempts):
            executor = BackendExecutor(
                self.scaling_config, self.backend,
                experiment_name=os.path.basename(exp_dir))
            try:
                executor.start()
                executor.start_training(
                    self.train_loop, self.train_loop_config,
                    resume_checkpoint=resume, datasets=self.datasets)
                ckpt_seq = len(history)
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    rank0 = results[0]
                    last_metrics = rank0["metrics"]
                    history.append(dict(last_metrics))
                    ckpt = self._collect_checkpoint(
                        results, exp_dir, ckpt_seq)
                    ckpt_seq += 1
                    if ckpt is not None:
                        manager.register(ckpt, last_metrics)
                        resume = manager.latest
                error = None
                break
            except Exception as e:  # worker death, report error, infra
                error = str(e)
                logger.warning(
                    "training attempt %d/%d failed: %s",
                    attempt + 1, attempts, e)
                resume = manager.latest or self.resume_from_checkpoint
            finally:
                executor.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=manager.latest,
            path=exp_dir,
            error=error,
            metrics_history=history,
            best_checkpoint=manager.best,
        )

    def _collect_checkpoint(self, results, exp_dir: str,
                            seq: int) -> Optional[Checkpoint]:
        """Move reported checkpoint dirs into the experiment dir. Multi-rank
        reports merge into one directory (each rank wrote distinct shard
        files — the orbax recipe)."""
        paths = [r["checkpoint_path"] for r in results
                 if r["checkpoint_path"]]
        if not paths:
            return None
        dest = os.path.join(exp_dir, f"checkpoint_{seq:06d}")
        os.makedirs(dest, exist_ok=True)
        for p in dict.fromkeys(paths):  # dedupe, keep order
            if os.path.abspath(p) == os.path.abspath(dest):
                continue
            if os.path.isdir(p):
                _merge_move_tree(p, dest)
        return Checkpoint(dest)

    def as_trainable(self):
        """Adapter for ray_tpu.tune: a function trainable closing over this
        trainer's configs; Tune overrides train_loop_config per trial."""
        base = self

        def trainable(config: dict):
            merged = dict(base.train_loop_config)
            merged.update(config)
            trainer = JaxTrainer(
                base.train_loop,
                train_loop_config=merged,
                scaling_config=base.scaling_config,
                run_config=base.run_config,
                backend=base.backend,
                datasets=base.datasets,
                resume_from_checkpoint=base.resume_from_checkpoint,
            )
            result = trainer.fit()
            if result.error:
                raise RuntimeError(result.error)
            return result.metrics

        trainable.__name__ = "jax_trainer"
        return trainable


# Alias matching the reference's family naming (TorchTrainer et al.)
DataParallelTrainer = JaxTrainer
