"""Mixture-of-Experts with expert parallelism (EP).

Reference gap: ray has no MoE/expert-parallel support (SURVEY §2.5 —
"EP: Absent"). This is the GSPMD formulation (Switch Transformer /
GShard): routing builds a dispatch tensor, expert computation is an
einsum over a leading expert dimension, and a sharding constraint on
the "expert" mesh axis makes XLA insert the token all-to-alls over ICI
— no hand-written collectives, and the dispatch/combine einsums land on
the MXU.

Capacity-based top-1 (Switch) and top-2 (GShard) routing with an
auxiliary load-balancing loss, exposed via flax's ``sow`` mechanism.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import _jax_compat


def _dispatch_tensors(router_probs, expert_idx, num_experts: int,
                      capacity: int, position_offset=None):
    """Build [N, E, C] dispatch (0/1) and combine (gate-weighted) tensors
    for one routing choice. Tokens beyond an expert's capacity drop.

    ``position_offset`` [E]: slots already occupied by a higher-priority
    routing choice (GShard: second choices queue behind all first
    choices, so top-1 and top-2 tokens never collide on a slot)."""
    n = expert_idx.shape[0]
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)
    # Position of each token within its expert's queue.
    pos = jnp.cumsum(onehot, axis=0) * onehot  # [N, E], 1-based
    if position_offset is not None:
        pos = pos + position_offset[None, :] * onehot
    keep = (pos > 0) & (pos <= capacity)
    pos_idx = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(
        jnp.sum(pos_idx * onehot.astype(jnp.int32), axis=-1),
        capacity, dtype=jnp.float32)  # [N, C]
    dispatch = (onehot * keep)[:, :, None] * cap_onehot[:, None, :]
    gates = jnp.sum(router_probs * onehot, axis=-1)  # [N]
    combine = dispatch * gates[:, None, None]
    return dispatch, combine


def load_balancing_loss(router_probs, expert_idx, num_experts: int):
    """Switch aux loss: E * dot(fraction_routed, mean_prob)."""
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(router_probs, axis=0)
    return num_experts * jnp.sum(density * density_proxy)


class MoELayer(nn.Module):
    """Expert-parallel FFN block.

    Expert weights carry a leading [E, ...] dimension; constraining the
    expert-payload tensors to P("expert") shards experts across the mesh
    and XLA lowers the dispatch einsum into an all-to-all over ICI.
    """

    num_experts: int
    ffn_dim: int
    k: int = 2  # 1 = Switch, 2 = GShard top-2
    capacity_factor: float = 1.25
    expert_axis: Optional[str] = "expert"
    router_jitter: float = 0.0

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        orig_shape = x.shape
        hidden = orig_shape[-1]
        tokens = x.reshape(-1, hidden)
        n = tokens.shape[0]
        e = self.num_experts
        capacity = max(1, int(math.ceil(
            n / e * self.capacity_factor * self.k)))

        logits = nn.Dense(e, use_bias=False, name="router")(tokens)
        if self.router_jitter and not deterministic:
            key = self.make_rng("router")
            logits = logits + jax.random.uniform(
                key, logits.shape, minval=-self.router_jitter,
                maxval=self.router_jitter)
        probs = jax.nn.softmax(logits, axis=-1)

        top1 = jnp.argmax(probs, axis=-1)
        dispatch, combine = _dispatch_tensors(probs, top1, e, capacity)
        aux = load_balancing_loss(probs, top1, e)
        if self.k == 2:
            probs2 = probs * (1.0 - jax.nn.one_hot(top1, e))
            top2 = jnp.argmax(probs2, axis=-1)
            # Second choices queue behind every first choice of the same
            # expert — without the offset, top-1 and top-2 tokens land on
            # the same slot and their activations sum.
            top1_counts = jnp.sum(
                jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
            d2, c2 = _dispatch_tensors(probs, top2, e, capacity,
                                       position_offset=top1_counts)
            dispatch = dispatch + d2
            combine = combine + c2
        self.sow("intermediates", "load_balancing_loss", aux)

        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, hidden, self.ffn_dim))
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, self.ffn_dim, hidden))

        # [N, E, C] x [N, H] -> [E, C, H]: the token all-to-all.
        expert_in = jnp.einsum("nec,nh->ech", dispatch, tokens)
        expert_in = _constrain(expert_in, P(self.expert_axis, None, None))
        h = jnp.einsum("ech,ehf->ecf", expert_in, w_in)
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecf,efh->ech", h, w_out)
        expert_out = _constrain(expert_out, P(self.expert_axis, None, None))
        # Combine back: [N, E, C] x [E, C, H] -> [N, H].
        out = jnp.einsum("nec,ech->nh", combine, expert_out)
        return out.reshape(orig_shape)


def _constrain(x, spec: P):
    """Apply a sharding constraint under a mesh context; no-op with no
    mesh (single-device tests). A mesh that lacks the requested axis is
    a loud error — silently dropping the constraint would quietly lose
    expert parallelism (every device holding all experts)."""
    wanted = {a for a in jax.tree.leaves(tuple(spec)) if a is not None}
    if not wanted:
        return x
    mesh = _jax_compat.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    missing = wanted - set(mesh.axis_names or ())
    if missing:
        raise ValueError(
            f"mesh {tuple(mesh.axis_names)} lacks axes {sorted(missing)} "
            f"required by this MoE layer's expert_axis")
    return jax.lax.with_sharding_constraint(x, spec)


def moe_aux_loss(intermediates) -> jnp.ndarray:
    """Sum all sown load-balancing losses from a flax intermediates
    collection (use: loss = task_loss + coef * moe_aux_loss(inter))."""
    total = 0.0
    flat = jax.tree.leaves(intermediates)
    for leaf in flat:
        total = total + jnp.sum(leaf)
    return total
