"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Net-new relative to the reference (SURVEY.md §5.7: ray has no sequence
parallelism; it only provides the collective substrate). Here they are
first-class mesh-axis strategies:

- **Ring attention**: q stays put; k/v shards rotate around the `sequence`
  mesh axis with `ppermute` (ICI neighbor exchange), each step combining a
  partial attention with the running online-softmax state. Communication
  overlaps compute step-for-step; memory per device is O(S/P).
- **Ulysses**: `all_to_all` swaps the sharded axis from sequence to heads,
  runs dense local attention (the Pallas flash kernel), and swaps back.
  Cheaper for moderate S, requires heads % P == 0.

Both are written to run inside `shard_map` over a mesh with a "sequence"
axis; `ring_attention`/`ulysses_attention` are the in-shard functions and
`make_sequence_parallel_attention` builds the shard_mapped callable.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel._jax_compat import shard_map

_NEG_INF = -1e30


def _partial_attention(q, k, v, q_offset, k_offset, sm_scale, causal):
    """One blockwise attention contribution with global-position causal
    masking. Shapes: q (B, Sq, H, D); k/v (B, Sk, H, D). Returns
    (unnormalized_out_f32, m_f32, l_f32)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,H,Sq,1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out, m, l


def ring_attention(q, k, v, axis_name: str = "sequence",
                   causal: bool = True, sm_scale: Optional[float] = None):
    """In-shard ring attention. q/k/v: local shards (B, S_local, H, D)."""
    d = q.shape[-1]
    s_local = q.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    B, _, H, _ = q.shape
    o0 = jnp.zeros((B, H, s_local, d), jnp.float32)
    m0 = jnp.full((B, H, s_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, s_local, 1), jnp.float32)

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size  # whose kv shard we hold now
        out_i, m_i, l_i = _partial_attention(
            q, k_cur, v_cur,
            q_offset=my_idx * s_local,
            k_offset=src_idx * s_local,
            sm_scale=sm_scale, causal=causal,
        )
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        o = o * alpha + out_i * beta
        l = l * alpha + l_i * beta
        # Rotate kv to the next device; skipped on the final step.
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m_new, l, k_next, v_next), None

    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    out = o / jnp.maximum(l, 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, S_local, H, D)


def ulysses_attention(q, k, v, axis_name: str = "sequence",
                      causal: bool = True, sm_scale: Optional[float] = None,
                      impl: str = "auto"):
    """In-shard Ulysses attention: all-to-all heads↔sequence swap."""
    from ray_tpu.ops.attention import attention

    # (B, S/P, H, D) -> (B, S, H/P, D)
    q = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                           tiled=True)
    k = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                           tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                           tiled=True)
    out = attention(q, k, v, causal=causal, sm_scale=sm_scale, impl=impl)
    # (B, S, H/P, D) -> (B, S/P, H, D)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def make_sequence_parallel_attention(mesh: Mesh, kind: str = "ring",
                                     causal: bool = True,
                                     axis_name: str = "sequence"):
    """Build a shard_mapped attention callable over `mesh`.

    Input/output layout: (batch, seq, heads, head_dim) with seq sharded on
    `axis_name` and batch sharded on data axes present in the mesh.
    """
    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    spec = P(batch_axes if batch_axes else None, axis_name, None, None)

    fn = ring_attention if kind == "ring" else ulysses_attention

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False,
    )
    def sp_attention(q, k, v):
        return fn(q, k, v, axis_name=axis_name, causal=causal)

    return sp_attention
