"""Device mesh construction for the standard parallelism axes.

The canonical mesh has up to five named axes — ("data", "fsdp", "tensor",
"sequence", "expert") — laid out so that the innermost axes map to
physically adjacent devices (ICI neighbors) where the highest-bandwidth
collectives run: tensor/sequence collectives are per-layer (latency
critical), fsdp all-gathers are per-step, data all-reduces amortize.

On a pod slice, `jax.devices()` is already ordered so that a row-major
reshape keeps ICI locality; `create_mesh` relies on that (the same recipe
as jax.experimental.mesh_utils for a single slice).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "tensor", "sequence", "expert")


@dataclass
class MeshConfig:
    """Sizes for each parallelism axis; -1 on `data` means "use the rest"."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    # Axis order, outermost first. DCN-spanning axes should be first.
    axis_order: Tuple[str, ...] = field(default=AXES)

    def resolve(self, num_devices: int) -> dict:
        sizes = {
            "data": self.data,
            "fsdp": self.fsdp,
            "tensor": self.tensor,
            "sequence": self.sequence,
            "expert": self.expert,
        }
        fixed = math.prod(v for v in sizes.values() if v > 0)
        n_auto = sum(1 for v in sizes.values() if v <= 0)
        if n_auto == 0:
            if fixed != num_devices:
                raise ValueError(
                    f"mesh axes {sizes} need {fixed} devices, have "
                    f"{num_devices}"
                )
            return sizes
        if num_devices % fixed != 0:
            raise ValueError(
                f"fixed axes use {fixed} devices which does not divide "
                f"{num_devices}"
            )
        auto = num_devices // fixed
        for k, v in sizes.items():
            if v <= 0:
                sizes[k] = auto
                auto = 1
        return sizes


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence] = None) -> Mesh:
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in config.axis_order)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, config.axis_order)


def local_mesh(**axis_sizes) -> Mesh:
    """Convenience: `local_mesh(data=2, tensor=4)` over local devices.
    Axis names outside the canonical five (e.g. ``stage`` for pipeline
    parallelism) build a custom mesh directly."""
    if all(a in AXES for a in axis_sizes):
        return create_mesh(MeshConfig(**axis_sizes))
    # Custom axes have no "-1 means the rest" resolution.
    bad = {a: s for a, s in axis_sizes.items() if s < 1}
    if bad:
        raise ValueError(f"custom mesh axes need explicit sizes >= 1: {bad}")
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[a] for a in names)
    n = math.prod(shape)
    if n > len(jax.devices()):
        raise ValueError(
            f"mesh {dict(axis_sizes)} needs {n} devices, "
            f"have {len(jax.devices())}")
    dev_array = np.array(jax.devices()[:n]).reshape(shape)
    return Mesh(dev_array, names)


def data_axes(mesh: Mesh) -> Optional[Tuple[str, ...]]:
    """Axes a batch dimension shards over (data + fsdp when present).
    Returns None (replicate) for meshes with no batch-carrying axis, so
    the result is always a valid PartitionSpec entry for ``mesh``."""
    axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names
                 and mesh.shape[a] > 1)
    if axes:
        return axes
    return ("data",) if "data" in mesh.axis_names else None
