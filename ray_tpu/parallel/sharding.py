"""Logical-axis sharding rules (GSPMD).

Parameters and activations are annotated with *logical* axis names; a rules
table maps logical names → mesh axes. Changing the parallelism strategy is
a rules-table swap, not a model change — the GSPMD equivalent of the
reference's per-strategy backends (reference: train/torch/config.py NCCL
DDP vs train_loop_utils.py FSDP wrap).

Canonical transformer layout (Llama-family):
    embedding  (vocab, embed)          -> ("vocab_shard", "embed")
    attn qkv   (embed, q_heads*dh)     -> ("embed", "heads")
    attn out   (q_heads*dh, embed)     -> ("heads", "embed")
    mlp in     (embed, ffn)            -> ("embed", "ffn")
    mlp out    (ffn, embed)            -> ("ffn", "embed")
    activation (batch, seq, embed)     -> ("batch", "seq", "embed_act")

FSDP shards the "embed" parameter axis over the fsdp mesh axis (ZeRO-3
equivalent: params all-gathered per layer by XLA); TP shards "heads"/"ffn"
over tensor; SP shards "seq" over sequence.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Default rules: full dp/fsdp/tp/sp composition.
LOGICAL_RULES: Rules = {
    "batch": ("data", "fsdp"),
    "seq": "sequence",
    "embed": "fsdp",
    "embed_act": None,
    "heads": "tensor",
    "kv_heads": "tensor",
    "ffn": "tensor",
    "vocab_shard": "tensor",
    "expert": "expert",
    "expert_ffn": "tensor",
    "layers": None,  # scanned-layer axis stays replicated
    "norm": None,
}


def spec_from_logical(logical_axes: Tuple[Optional[str], ...],
                      rules: Optional[Rules] = None) -> P:
    rules = rules or LOGICAL_RULES
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
        else:
            out.append(rules.get(name))
    return P(*out)


def logical_sharding(mesh: Mesh, logical_axes: Tuple[Optional[str], ...],
                     rules: Optional[Rules] = None) -> NamedSharding:
    spec = spec_from_logical(logical_axes, rules)
    # Drop mesh axes the mesh doesn't have (e.g. tests with a 1-axis mesh).
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.axis_names)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(entry if entry in mesh.axis_names else None)
    return NamedSharding(mesh, P(*cleaned))


def with_logical_constraint(x, logical_axes: Tuple[Optional[str], ...],
                            mesh: Optional[Mesh] = None,
                            rules: Optional[Rules] = None):
    """In-graph activation sharding hint (inside jit)."""
    mesh = mesh or _current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules)
    )


def _current_mesh() -> Optional[Mesh]:
    try:
        from jax.interpreters import pxla

        mesh = pxla.thread_resources.env.physical_mesh
        if mesh.empty:
            return None
        return mesh
    except Exception:
        return None


def shard_params(params, mesh: Mesh, logical_axes_tree,
                 rules: Optional[Rules] = None):
    """Device-put a param pytree according to a matching tree of logical
    axis tuples."""
    shardings = jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        logical_axes_tree,
        is_leaf=lambda v: isinstance(v, tuple)
        and all(isinstance(e, (str, type(None))) for e in v),
    )
    return jax.device_put(params, shardings)


def infer_param_logical_axes(params):
    """Heuristic logical axes for a flax param tree, keyed on path + shape.

    Used when a model doesn't carry explicit annotations; the flagship
    models annotate explicitly via nn.with_partitioning instead.
    """

    def classify(path: str, leaf):
        ndim = getattr(leaf, "ndim", 0)
        path_l = path.lower()
        if ndim <= 1:
            return (("norm",) if ndim else ())[:ndim] or (None,) * ndim
        if "embed" in path_l and ndim == 2:
            return ("vocab_shard", "embed")
        if any(k in path_l for k in ("wq", "wk", "wv", "query", "key",
                                     "value")):
            return ("embed", "heads")
        if any(k in path_l for k in ("wo", "out_proj", "attn_out")):
            return ("heads", "embed")
        if any(k in path_l for k in ("w1", "w3", "gate", "up")):
            return ("embed", "ffn")
        if any(k in path_l for k in ("w2", "down")):
            return ("ffn", "embed")
        if ndim == 2:
            return ("embed", None)
        return (None,) * ndim

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        out[key] = classify(key, leaf)
    return out
