"""Version-portable ``shard_map`` / ambient-mesh helpers.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where the
replication-check kwarg is ``check_rep``) to ``jax.shard_map`` (where it
was renamed ``check_vma``); likewise ``jax.set_mesh`` /
``jax.sharding.get_abstract_mesh`` replaced the legacy ``with mesh:``
resource-env context. The container pins whichever jax the image bakes
in, so resolve the callables at import time.
"""

from __future__ import annotations

import jax

try:
    _shard_map = jax.shard_map
    _CHECK_KWARG = "check_vma"
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KWARG = "check_rep"


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True):
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs,
              _CHECK_KWARG: check_vma}
    if f is None:
        return lambda fn: _shard_map(fn, **kwargs)
    return _shard_map(f, **kwargs)


try:
    set_mesh = jax.set_mesh
except AttributeError:
    def set_mesh(mesh):
        # Legacy jax: a Mesh is its own context manager (resource env),
        # and bare-PartitionSpec sharding constraints resolve against it.
        return mesh


try:
    get_abstract_mesh = jax.sharding.get_abstract_mesh
except AttributeError:
    from jax._src.mesh import thread_resources as _thread_resources

    def get_abstract_mesh():
        # Legacy jax: the ambient mesh entered via ``with mesh:``.
        # Returns an empty Mesh (``.empty`` True) when none is active,
        # matching the modern API's contract closely enough for axis
        # checks.
        return _thread_resources.env.physical_mesh
