"""Pipeline parallelism: SPMD collective-permute pipelining.

Reference gap: ray has no pipeline-parallel training (SURVEY §2.5 —
"PP: Absent"; compiled DAGs are its general substrate). The TPU-native
formulation is not actor channels but a *single SPMD program*: stages
live on a mesh axis, microbatch activations circulate stage→stage with
``lax.ppermute`` inside a ``lax.scan`` over ticks, and the whole
pipeline — bubbles and all — compiles to one XLA executable with
point-to-point ICI transfers (the scaling-book / praxis recipe).

Layout: stage-stacked params [S, ...] sharded P("stage"); at tick t,
stage s processes microbatch t - s (the GPipe schedule).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel._jax_compat import shard_map


def stack_stage_params(param_trees):
    """Stack per-stage param pytrees into [S, ...] leaves (shard the
    leading axis on the "stage" mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def make_pipeline(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  mesh: Mesh, *, num_microbatches: int,
                  axis_name: str = "stage"):
    """Build pipelined_apply(stacked_params, x) -> y.

    - ``stage_fn(stage_params, activations)`` applies ONE stage.
    - ``stacked_params``: pytree with leading stage axis [S, ...].
    - ``x``: [num_microbatches, microbatch, ...] global batch.
    Output has x's shape (activations shape must be stage-invariant).
    """
    num_stages = mesh.shape[axis_name]
    m = num_microbatches

    def per_device(params_blk, x):
        # shard_map hands each device its stage's params with a leading
        # block axis of size 1.
        params_s = jax.tree.map(lambda a: jnp.squeeze(a, 0), params_blk)
        s = jax.lax.axis_index(axis_name)
        state0 = jnp.zeros_like(x[0])
        outputs0 = jnp.zeros_like(x)
        last = num_stages - 1
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 ingests microbatch t (clamped replay past the end
            # is garbage that never reaches an output slot).
            x_t = x[jnp.clip(t, 0, m - 1)]
            state = jnp.where(s == 0, x_t, state)
            y = stage_fn(params_s, state)
            mb_idx = t - last
            write = (s == last) & (mb_idx >= 0)
            idx = jnp.clip(mb_idx, 0, m - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, y,
                          jax.lax.dynamic_index_in_dim(
                              outputs, idx, 0, keepdims=False)),
                idx, 0)
            state = jax.lax.ppermute(y, axis_name, perm)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(m + num_stages - 1))
        # Only the last stage holds real outputs; psum broadcasts them
        # (all other stages contributed zeros).
        mask = jnp.where(s == last, 1.0, 0.0).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis_name)

    # P(axis_name) applies as a prefix spec to every param leaf.
    sharded = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )

    def pipelined_apply(stacked_params, x):
        if x.shape[0] != m:
            raise ValueError(
                f"expected leading microbatch dim {m}, got {x.shape[0]}")
        return sharded(stacked_params, x)

    return pipelined_apply


def stage_sharding(mesh: Mesh, axis_name: str = "stage") -> NamedSharding:
    """Sharding for stacked stage params: leading axis over the stage
    mesh axis."""
    return NamedSharding(mesh, P(axis_name))
