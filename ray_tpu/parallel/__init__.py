"""Parallelism strategies as mesh-axis presets (SURVEY.md §2.5).

Every strategy the reference supports (or lacks and we add) is expressed as
a named mesh axis + sharding rules, not a framework fork:

- **dp**   data parallel         (reference: Train NCCL DDP — train/torch/config.py)
- **fsdp** sharded data parallel (reference: pass-through FSDP — train_loop_utils.py:184)
- **tp**   tensor parallel       (absent in reference; net-new)
- **sp**   sequence/context parallel — ring attention / Ulysses (net-new)
- **ep**   expert parallel       (net-new)
- **pp**   pipeline parallel     (compiled-DAG substrate in reference)
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    create_mesh,
    local_mesh,
)
from ray_tpu.parallel.moe import MoELayer, moe_aux_loss
from ray_tpu.parallel.pipeline import (
    make_pipeline,
    stack_stage_params,
    stage_sharding,
)
from ray_tpu.parallel.sharding import (
    LOGICAL_RULES,
    logical_sharding,
    shard_params,
    with_logical_constraint,
)

__all__ = [
    "LOGICAL_RULES",
    "MeshConfig",
    "MoELayer",
    "create_mesh",
    "local_mesh",
    "logical_sharding",
    "make_pipeline",
    "moe_aux_loss",
    "shard_params",
    "stack_stage_params",
    "stage_sharding",
    "with_logical_constraint",
]
