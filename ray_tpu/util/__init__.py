"""ray_tpu.util — utilities over the core (reference: python/ray/util)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.cluster_utils import Cluster
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.timeline import timeline

__all__ = [
    "ActorPool",
    "Cluster",
    "Empty",
    "Full",
    "Queue",
    "timeline",
]
