"""Declarative SLO/alert rules evaluated head-side over metrics history.

A rule names a telemetry-catalog metric, a window aggregate (``delta``
/``rate`` for counters, ``last``/``max``/``min``/``avg`` for gauges,
``pNN``/``delta``/``rate`` for histograms — see
:meth:`MetricsHistoryStore.window_agg`), a comparison against a
threshold, and a ``for_s`` sustain window. The engine walks a
pending → firing → resolved lifecycle per (rule, series tag set):
a breach must hold for ``for_s`` seconds before the alert fires, and
both transitions record a flight-recorder event under the ``alert``
subsystem carrying the offending series window as evidence, plus a
timeline event, the ``ray_tpu_alerts_firing`` gauge, and the
``ray_tpu_alerts_transitions_total`` counter.

Rules are validated against ``telemetry.CATALOG`` at registration:
a typo'd metric name, an undeclared tag key, or an aggregate that does
not fit the metric kind raises ``ValueError`` — the catalog lint in
tier-1 holds the DEFAULT_RULES to the same bar.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: Aggregates accepted per catalog metric kind.
AGGS_BY_KIND = {
    "counter": ("delta", "rate", "last"),
    "gauge": ("last", "max", "min", "avg"),
    "histogram": ("p50", "p90", "p95", "p99", "delta", "rate"),
}

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}

#: Evidence payload caps: full window in the episode record, a compact
#: tail in the flight-recorder tags (the ring ships over RPC).
_EVIDENCE_POINTS = 64
_EVIDENCE_TAG_CHARS = 900


@dataclass
class AlertRule:
    """One SLO predicate over a catalog metric."""

    name: str
    metric: str
    agg: str
    op: str
    threshold: float
    window_s: float = 60.0
    for_s: float = 0.0
    severity: str = "warn"  # "warn" | "error"
    tags: Dict[str, str] = field(default_factory=dict)
    description: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name, "metric": self.metric, "agg": self.agg,
            "op": self.op, "threshold": self.threshold,
            "window_s": self.window_s, "for_s": self.for_s,
            "severity": self.severity, "tags": dict(self.tags),
            "description": self.description,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRule":
        return cls(
            name=str(d["name"]), metric=str(d["metric"]),
            agg=str(d["agg"]), op=str(d["op"]),
            threshold=float(d["threshold"]),
            window_s=float(d.get("window_s", 60.0)),
            for_s=float(d.get("for_s", 0.0)),
            severity=str(d.get("severity", "warn")),
            tags=dict(d.get("tags") or {}),
            description=str(d.get("description", "")),
        )


def validate_rule(rule: AlertRule, catalog: Optional[dict] = None) -> None:
    """Reject rules that reference anything outside the telemetry
    catalog (metric name, tag keys) or whose aggregate does not fit the
    metric's kind. Raises ``ValueError``."""
    if catalog is None:
        from ray_tpu.util import telemetry

        catalog = telemetry.CATALOG
    if not rule.name:
        raise ValueError("alert rule needs a name")
    spec = catalog.get(rule.metric)
    if spec is None:
        raise ValueError(
            f"alert rule {rule.name!r}: metric {rule.metric!r} is not in "
            f"the telemetry catalog")
    kind, _desc, tag_keys = spec[0], spec[1], spec[2]
    allowed = AGGS_BY_KIND.get(kind, ())
    if rule.agg not in allowed:
        raise ValueError(
            f"alert rule {rule.name!r}: agg {rule.agg!r} is not valid "
            f"for {kind} metric {rule.metric!r} (allowed: {allowed})")
    if rule.op not in _OPS:
        raise ValueError(
            f"alert rule {rule.name!r}: unknown op {rule.op!r}")
    for k in rule.tags:
        if k not in tag_keys:
            raise ValueError(
                f"alert rule {rule.name!r}: tag {k!r} is not declared "
                f"for {rule.metric!r} (declared: {tuple(tag_keys)})")
    if rule.window_s <= 0:
        raise ValueError(f"alert rule {rule.name!r}: window_s must be > 0")
    if rule.for_s < 0:
        raise ValueError(f"alert rule {rule.name!r}: for_s must be >= 0")
    if rule.severity not in ("warn", "error"):
        raise ValueError(
            f"alert rule {rule.name!r}: severity must be warn|error")


def default_rules() -> List[AlertRule]:
    """The shipped SLO rule set. Thresholds are deliberately loose —
    they flag pathology, not tuning opportunities; tighten per
    deployment via ``alerts_put_rule``."""
    return [
        AlertRule(
            "train_rank_stalled",
            "ray_tpu_train_step_heartbeat_age_seconds", "max", ">",
            30.0, window_s=120.0, for_s=5.0, severity="error",
            description="A train rank's device step counter stopped "
            "advancing (per-rank; precursor of a gang hang abort)."),
        AlertRule(
            "circuit_breaker_open",
            "ray_tpu_circuit_breaker_transitions_total", "delta", ">=",
            1.0, window_s=60.0, for_s=0.0, severity="warn",
            tags={"state": "open"},
            description="A circuit breaker opened in the window; "
            "resolves when no new opens age in."),
        AlertRule(
            "serve_ttft_p99_high",
            "ray_tpu_serve_stream_ttft_seconds", "p99", ">",
            2.0, window_s=120.0, for_s=10.0, severity="warn",
            description="Streaming time-to-first-token p99 over target "
            "(per deployment)."),
        AlertRule(
            "engine_queue_backlog",
            "ray_tpu_serve_engine_queue_depth", "avg", ">",
            64.0, window_s=60.0, for_s=15.0, severity="warn",
            description="A replica engine's admission queue stayed deep "
            "(sustained backlog, not a burst)."),
        AlertRule(
            "serve_shed_rate",
            "ray_tpu_serve_replica_sheds_total", "rate", ">",
            1.0, window_s=60.0, for_s=10.0, severity="warn",
            description="Replicas are being shed from routing faster "
            "than one per second (breaker churn)."),
        AlertRule(
            "node_suspect",
            "ray_tpu_gcs_nodes", "max", ">=",
            1.0, window_s=60.0, for_s=3.0, severity="warn",
            tags={"state": "SUSPECT"},
            description="Nodes sat in the SUSPECT death-grace window "
            "(node churn; every DEAD transition passes through here)."),
        AlertRule(
            "object_spill_rate",
            "ray_tpu_object_spilled_bytes_total", "rate", ">",
            64.0 * 1024 * 1024, window_s=60.0, for_s=10.0,
            severity="warn",
            description="Object store spilling to disk faster than "
            "64 MiB/s (memory pressure)."),
        AlertRule(
            "profiler_overhead",
            "ray_tpu_profiler_overhead_ratio", "max", ">",
            0.05, window_s=120.0, for_s=30.0, severity="warn",
            description="Continuous profiler overhead above 5% of wall "
            "time on some process."),
        AlertRule(
            "event_loop_lag",
            "ray_tpu_event_loop_lag_seconds", "p99", ">",
            0.25, window_s=60.0, for_s=5.0, severity="warn",
            description="An event loop's lag-probe p99 stayed above "
            "250 ms (per process+loop; a starved loop stalls every "
            "RPC it serves)."),
        AlertRule(
            "rpc_handler_slow",
            "ray_tpu_rpc_server_handler_seconds", "p99", ">",
            1.0, window_s=60.0, for_s=10.0, severity="warn",
            description="Server-side handler-time p99 above 1 s for "
            "some RPC method (control-plane handlers should be "
            "milliseconds)."),
    ]


class AlertEngine:
    """Firing/resolved state machines over a MetricsHistoryStore."""

    def __init__(self, store, rules: Optional[List[AlertRule]] = None,
                 clock=time.time, max_episodes: int = 256):
        self._store = store
        self._clock = clock
        self.rules: Dict[str, AlertRule] = {}
        #: (rule name, series tag tuple) -> state dict.
        self._states: Dict[tuple, dict] = {}
        self.episodes: deque = deque(maxlen=max_episodes)
        for r in rules or ():
            self.add_rule(r)

    def add_rule(self, rule: AlertRule) -> None:
        validate_rule(rule)
        self.rules[rule.name] = rule

    def remove_rule(self, name: str) -> None:
        self.rules.pop(name, None)
        for key in [k for k in self._states if k[0] == name]:
            del self._states[key]

    # -- evaluation ------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Advance every rule's state machines; returns transitions."""
        now = self._clock() if now is None else now
        transitions: List[dict] = []
        for rule in list(self.rules.values()):
            try:
                rows = self._store.window_agg(
                    rule.metric, rule.agg, rule.window_s, now=now,
                    tags=rule.tags or None)
            except Exception:  # lint: allow-silent(a malformed series must not stop the sweep; the rule simply sees no data)
                rows = []
            op = _OPS[rule.op]
            live = set()
            for row in rows:
                if not op(row["value"], rule.threshold):
                    continue
                tk = tuple(sorted(row["tags"].items()))
                key = (rule.name, tk)
                live.add(key)
                st = self._states.get(key)
                if st is None:
                    st = self._states[key] = {
                        "state": "pending", "since": now}
                st["value"] = row["value"]
                if (st["state"] == "pending"
                        and now - st["since"] >= rule.for_s):
                    self._fire(rule, tk, st, now, transitions)
            for key in [k for k, st in self._states.items()
                        if k[0] == rule.name and k not in live]:
                st = self._states.pop(key)
                if st["state"] == "firing":
                    self._resolve(rule, key[1], st, now, transitions)
        self._publish_gauges()
        return transitions

    def _evidence(self, rule: AlertRule, tk: tuple,
                  now: float) -> List[list]:
        # A breach sustained by gauge carry-forward can have no point
        # inside the rule window itself; widen until the series tail
        # shows up — a fired alert with empty evidence is useless.
        for window_s in (rule.window_s, 8 * rule.window_s + 600.0):
            try:
                rows = self._store.query_points(
                    rule.metric, window_s, now=now, tags=dict(tk),
                    max_points=_EVIDENCE_POINTS)
            except Exception:  # lint: allow-silent(evidence is best-effort decoration on the transition)
                return []
            for row in rows:
                if (tuple(sorted(row["tags"].items())) == tk
                        and row["points"]):
                    return [[round(t, 3), v] for t, v in row["points"]]
        return []

    def _fire(self, rule: AlertRule, tk: tuple, st: dict, now: float,
              transitions: List[dict]) -> None:
        from ray_tpu.util import flight_recorder, telemetry

        evidence = self._evidence(rule, tk, now)
        episode = {
            "rule": rule.name, "metric": rule.metric,
            "agg": rule.agg, "op": rule.op,
            "threshold": rule.threshold,
            "severity": rule.severity, "tags": dict(tk),
            "value": st["value"], "pending_ts": st["since"],
            "fired_ts": now, "resolved_ts": None,
            "window_s": rule.window_s,
            "evidence": evidence,
            "description": rule.description,
        }
        st["state"] = "firing"
        st["fired_at"] = now
        st["episode"] = episode
        self.episodes.append(episode)
        transitions.append({"event": "fired", "episode": episode})
        flight_recorder.record(
            "alert", "fired",
            severity="error" if rule.severity == "error" else "warn",
            rule=rule.name, metric=rule.metric,
            series=_fmt_tags(tk), value=round(float(st["value"]), 6),
            threshold=rule.threshold,
            window=json.dumps(evidence[-16:])[:_EVIDENCE_TAG_CHARS])
        telemetry.inc("ray_tpu_alerts_transitions_total", 1,
                      {"rule": rule.name, "state": "fired"})
        telemetry.event("alerts", f"{rule.name} fired", ts=now,
                        args={"series": _fmt_tags(tk),
                              "value": st["value"]})

    def _resolve(self, rule: AlertRule, tk: tuple, st: dict,
                 now: float, transitions: List[dict]) -> None:
        from ray_tpu.util import flight_recorder, telemetry

        episode = st.get("episode") or {}
        episode["resolved_ts"] = now
        transitions.append({"event": "resolved", "episode": episode})
        flight_recorder.record(
            "alert", "resolved", severity="info",
            rule=rule.name, metric=rule.metric, series=_fmt_tags(tk),
            duration_s=round(now - st.get("fired_at", now), 3),
            window=json.dumps(self._evidence(rule, tk, now)[-16:])
            [:_EVIDENCE_TAG_CHARS])
        telemetry.inc("ray_tpu_alerts_transitions_total", 1,
                      {"rule": rule.name, "state": "resolved"})
        telemetry.event("alerts", f"{rule.name} resolved", ts=now,
                        args={"series": _fmt_tags(tk)})

    def _publish_gauges(self) -> None:
        try:
            from ray_tpu.util import telemetry

            counts = {name: 0 for name in self.rules}
            for (rule_name, _tk), st in self._states.items():
                if st["state"] == "firing":
                    counts[rule_name] = counts.get(rule_name, 0) + 1
            for name, n in counts.items():
                telemetry.set_gauge("ray_tpu_alerts_firing", n,
                                    {"rule": name})
        except Exception:  # lint: allow-silent(gauge publication is decoration; the state machines are authoritative)
            pass

    # -- introspection ---------------------------------------------------

    def firing(self) -> List[dict]:
        out = []
        for (rule_name, tk), st in self._states.items():
            if st["state"] != "firing":
                continue
            rule = self.rules.get(rule_name)
            out.append({
                "rule": rule_name, "tags": dict(tk),
                "value": st.get("value"),
                "fired_ts": st.get("fired_at"),
                "severity": rule.severity if rule else "warn",
                "metric": rule.metric if rule else "",
                "description": rule.description if rule else "",
            })
        out.sort(key=lambda r: r.get("fired_ts") or 0.0)
        return out

    def state(self) -> dict:
        return {
            "enabled": True,
            "firing": self.firing(),
            "episodes": list(self.episodes)[::-1],  # newest first
            "rules": [r.to_dict() for r in self.rules.values()],
        }

    # -- journal ---------------------------------------------------------

    def journal_state(self) -> dict:
        """JSONable open-alert state for the head's experiment-state
        journal: the episode history plus per-series state machines
        (episodes referenced by firing states are carried by identity
        through ``episode_index``, so resolve-after-restore stamps the
        same episode record the journal stored)."""
        ep_list = list(self.episodes)
        ep_ids = {id(ep): i for i, ep in enumerate(ep_list)}
        states = []
        for (rule_name, tk), st in self._states.items():
            row = {k: v for k, v in st.items() if k != "episode"}
            ep = st.get("episode")
            row["episode_index"] = ep_ids.get(id(ep)) if ep else None
            states.append([rule_name, [list(p) for p in tk], row])
        return {"episodes": ep_list, "states": states}

    def restore(self, data: dict) -> int:
        """Reload ``journal_state()`` output after a head restart;
        returns state machines restored. Episodes for unknown rules are
        kept (history is history); state machines for unknown rules are
        dropped (the rule set is authoritative). Restored firing states
        resolve normally once fresh pushes show the breach is gone —
        the first post-restore evaluate() should be delayed past one
        push interval so live-but-silent series aren't insta-resolved."""
        ep_list = [dict(ep) for ep in data.get("episodes", [])]
        self.episodes.extend(ep_list)
        restored = 0
        for rule_name, tk, row in data.get("states", []):
            if rule_name not in self.rules:
                continue
            st = dict(row)
            idx = st.pop("episode_index", None)
            if idx is not None and 0 <= idx < len(ep_list):
                st["episode"] = ep_list[idx]
            key = (rule_name, tuple(tuple(p) for p in tk))
            self._states[key] = st
            restored += 1
        return restored


def _fmt_tags(tk: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in tk) or "-"
