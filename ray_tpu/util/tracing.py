"""Distributed tracing: spans across task boundaries.

Reference: python/ray/util/tracing/tracing_helper.py:293 — ray wraps
remote calls in client spans and smuggles the trace context to the
worker (``_ray_trace_ctx``), where execution runs in a consumer span.
Here the context rides a hidden task kwarg as a W3C ``traceparent``
carrier — no task-protocol change, no scheduling-key impact — and the
worker's span parents correctly across processes and hosts.

Backends, picked automatically:
- **OpenTelemetry SDK** when installed (spans flow to the configured
  exporter — OTLP via OTEL_EXPORTER_OTLP_ENDPOINT, console via
  RAY_TPU_TRACE_CONSOLE, or one passed to ``setup_tracing``).
- **Built-in mini tracer** otherwise (this image ships only
  opentelemetry-api): real trace/span ids, W3C traceparent propagation,
  spans appended to ``RAY_TPU_TRACE_FILE`` as JSON lines and readable
  via ``get_recorded_spans()``.

Usage:
    from ray_tpu.util import tracing
    tracing.setup_tracing(service_name="my-app")
    ... ray_tpu.get(f.remote()) ...   # submit/execute spans auto-emitted
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_enabled = False
_backend = None  # "otel" | "mini"
_otel_tracer = None


# ---------------------------------------------------------------------------
# mini tracer (stdlib-only)
# ---------------------------------------------------------------------------

class _MiniSpan:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "attributes")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.time()
        self.end: Optional[float] = None
        self.attributes: Dict[str, str] = {}

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start": self.start, "end": self.end,
                "attributes": self.attributes}


# Task-local, not thread-local: spans are held across awaits (a Serve
# proxy handler, an RPC call awaiting its reply), and on one shared
# event loop a threading.local would leak the open span into every
# other coroutine interleaved with it — concurrent requests would merge
# into one trace. Each asyncio task (and each plain thread) gets its
# own context.
_current_span: "contextvars.ContextVar[Optional[_MiniSpan]]" = (
    contextvars.ContextVar("ray_tpu_mini_span", default=None))
_recorded: List[_MiniSpan] = []
_record_lock = threading.Lock()


def _current_mini() -> Optional[_MiniSpan]:
    return _current_span.get()


def get_recorded_spans() -> List[dict]:
    """Mini-tracer backend: every finished span in this process."""
    with _record_lock:
        return [s.to_dict() for s in _recorded]


def _record(span: _MiniSpan):
    span.end = time.time()
    with _record_lock:
        _recorded.append(span)
        if len(_recorded) > 10_000:
            del _recorded[:5_000]
    path = os.environ.get("RAY_TPU_TRACE_FILE")
    if path:
        try:
            with open(path, "a") as f:
                f.write(json.dumps(span.to_dict()) + "\n")
        except OSError:
            pass


@contextmanager
def _mini_span(name: str, trace_id: Optional[str],
               parent_id: Optional[str]):
    parent = _current_mini()
    if trace_id is None:
        trace_id = parent.trace_id if parent else secrets.token_hex(16)
    if parent_id is None and parent is not None:
        parent_id = parent.span_id
    span = _MiniSpan(name, trace_id, secrets.token_hex(8), parent_id)
    token = _current_span.set(span)
    try:
        yield span
    finally:
        try:
            _current_span.reset(token)
        except ValueError:
            # Token from another context (exotic executor reuse): just
            # clear rather than corrupt the stack.
            _current_span.set(None)
        _record(span)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def setup_tracing(service_name: str = "ray_tpu",
                  exporter=None) -> bool:
    """Idempotent per process. Returns True when tracing is active."""
    global _enabled, _backend, _otel_tracer
    if _enabled:
        return True
    try:
        from opentelemetry import trace
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import (
            BatchSpanProcessor,
            ConsoleSpanExporter,
            SimpleSpanProcessor,
        )

        provider = TracerProvider(
            resource=Resource.create({"service.name": service_name}))
        if exporter is not None:
            provider.add_span_processor(SimpleSpanProcessor(exporter))
        elif os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT"):
            from opentelemetry.exporter.otlp.proto.grpc.trace_exporter \
                import OTLPSpanExporter

            provider.add_span_processor(
                BatchSpanProcessor(OTLPSpanExporter()))
        elif os.environ.get("RAY_TPU_TRACE_CONSOLE"):
            provider.add_span_processor(
                SimpleSpanProcessor(ConsoleSpanExporter()))
        trace.set_tracer_provider(provider)
        _otel_tracer = trace.get_tracer("ray_tpu")
        _backend = "otel"
    except Exception:
        _backend = "mini"  # api-only install (or no otel at all)
    _enabled = True
    os.environ["RAY_TPU_TRACING_ENABLED"] = "1"
    logger.info("tracing enabled (backend=%s)", _backend)
    return True


def is_enabled() -> bool:
    return _enabled


def backend() -> Optional[str]:
    return _backend


def maybe_setup_worker_tracing():
    """Called on the worker execution path: enable when the driver
    enabled tracing (the flag rides the spawn env)."""
    if os.environ.get("RAY_TPU_TRACING_ENABLED") == "1" and not _enabled:
        setup_tracing(service_name="ray_tpu.worker")


def inject_context() -> Optional[Dict[str, str]]:
    """The CURRENT span context as a W3C carrier dict (or None)."""
    if not _enabled:
        return None
    if _backend == "otel":
        try:
            from opentelemetry import propagate

            carrier: Dict[str, str] = {}
            propagate.inject(carrier)
            return carrier or None
        except Exception:
            return None
    span = _current_mini()
    if span is None:
        return None
    return {"traceparent":
            f"00-{span.trace_id}-{span.span_id}-01"}


def _parse_traceparent(carrier: Optional[Dict[str, str]]):
    if not carrier:
        return None, None
    try:
        _, trace_id, span_id, _ = carrier["traceparent"].split("-")
        return trace_id, span_id
    except (KeyError, ValueError):
        return None, None


@contextmanager
def span(name: str, carrier: Optional[Dict[str, str]] = None):
    """Generic span: parents to ``carrier`` when given (cross-process /
    cross-thread propagation — Serve proxy -> router -> replica, RPC
    client -> server), else to the calling thread's current span."""
    if not _enabled:
        yield None
        return
    if _backend == "otel":
        ctx = None
        if carrier:
            try:
                from opentelemetry import propagate

                ctx = propagate.extract(carrier)
            except Exception:
                ctx = None
        with _otel_tracer.start_as_current_span(name, context=ctx) as s:
            yield s
        return
    trace_id, parent_id = _parse_traceparent(carrier)
    with _mini_span(name, trace_id, parent_id) as s:
        yield s


@contextmanager
def submit_span(name: str):
    """Producer-side span around a remote submission."""
    if not _enabled:
        yield None
        return
    if _backend == "otel":
        with _otel_tracer.start_as_current_span(f"submit {name}") as s:
            yield s
        return
    with _mini_span(f"submit {name}", None, None) as s:
        yield s


@contextmanager
def task_span(name: str, carrier: Optional[Dict[str, str]]):
    """Consumer-side span around task execution, parented to the
    submitter's span through the propagated carrier."""
    if not _enabled:
        yield None
        return
    if _backend == "otel":
        ctx = None
        if carrier:
            try:
                from opentelemetry import propagate

                ctx = propagate.extract(carrier)
            except Exception:
                ctx = None
        with _otel_tracer.start_as_current_span(f"execute {name}",
                                                context=ctx) as s:
            yield s
        return
    trace_id, parent_id = _parse_traceparent(carrier)
    with _mini_span(f"execute {name}", trace_id, parent_id) as s:
        yield s
